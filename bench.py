#!/usr/bin/env python
"""Benchmark — flagship Transformer MT workload, tokens/sec/chip.

Protocol per BASELINE.md: the reference publishes no numbers; its contract is
self-timed training throughput (``pytorch_machine_translator.py:199-205``
times batches of 32 × 200-token sentences). Here the same workload (reference
hypers: d_model=512, ffn=1024, heads=8, layers=1, seq=200, batch=32/chip,
Multi30k-scale vocabs) runs as a data-parallel jitted train step in bfloat16,
and ``vs_baseline`` is the ratio against the reference-equivalent PyTorch
model (torch.nn.Transformer, same shapes, Adam) measured on CPU in-process —
the reference's own engine on the hardware it targets (CPU-only end to end,
SURVEY.md §3 observation b).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

if os.environ.get("BENCH_PLATFORM"):  # e.g. "cpu" for hardware-free smoke runs
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_apache_spark_tpu.models import Transformer, TransformerConfig
from machine_learning_apache_spark_tpu.parallel import DATA_AXIS, make_mesh, shard_params
from machine_learning_apache_spark_tpu.train.losses import masked_token_cross_entropy
from machine_learning_apache_spark_tpu.train.state import TrainState, make_optimizer

SEQ = 200
BATCH_PER_CHIP = int(os.environ.get("BENCH_BATCH", "32"))
SRC_VOCAB = 8192
TRG_VOCAB = 10240
WARMUP = int(os.environ.get("BENCH_WARMUP", "5"))
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
TRIALS = int(os.environ.get("BENCH_TRIALS", "3"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_jax() -> float:
    n_chips = jax.device_count()
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = TransformerConfig(
        src_vocab_size=SRC_VOCAB,
        trg_vocab_size=TRG_VOCAB,
        max_len=SEQ,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )
    model = Transformer(cfg)
    mesh = make_mesh({DATA_AXIS: n_chips})
    batch = BATCH_PER_CHIP * n_chips

    rng = jax.random.key(0)
    src = jax.random.randint(rng, (batch, SEQ), 1, SRC_VOCAB, dtype=jnp.int32)
    trg = jax.random.randint(rng, (batch, SEQ), 1, TRG_VOCAB, dtype=jnp.int32)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    src, trg = jax.device_put(src, sharding), jax.device_put(trg, sharding)

    params = shard_params(model.init(jax.random.key(1), src[:2], trg[:2])["params"], mesh)
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("adam", 1e-3)
    )

    def loss_fn(params, src, trg, rng):
        logits = model.apply(
            {"params": params},
            src,
            trg[:, :-1],
            deterministic=False,
            rngs={"dropout": rng},
        )
        return masked_token_cross_entropy(logits, trg[:, 1:], cfg.pad_id)

    @jax.jit
    def step(state, src, trg, rng):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, src, trg, rng)
        return state.apply_gradients(grads), loss

    rngs = jax.random.split(jax.random.key(2), WARMUP + TRIALS * STEPS)
    for i in range(WARMUP):
        state, loss = step(state, src, trg, rngs[i])
    jax.block_until_ready(state.params)
    log(f"jax warmup done on {n_chips} × {jax.devices()[0].platform}")

    # Best of TRIALS timing windows: the tunneled dev chip is shared, so a
    # single window can be dominated by neighbor noise; the max is the
    # stable estimate of what the program actually sustains.
    best = 0.0
    for t in range(TRIALS):
        t0 = time.perf_counter()
        for i in range(STEPS):
            state, loss = step(state, src, trg, rngs[WARMUP + t * STEPS + i])
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        tps_chip = batch * SEQ * STEPS / dt / n_chips
        log(f"jax trial {t}: {STEPS} steps in {dt:.3f}s → "
            f"{tps_chip:,.0f} tokens/sec/chip (loss {float(loss):.3f})")
        best = max(best, tps_chip)
    return best


def bench_torch_baseline() -> float | None:
    """Reference-equivalent engine: torch.nn.Transformer, same shapes, CPU."""
    if os.environ.get("BENCH_SKIP_TORCH"):
        return None
    try:
        import torch
        import torch.nn as tnn

        torch.manual_seed(0)
        d, steps = 512, int(os.environ.get("BENCH_TORCH_STEPS", "3"))
        batch = min(BATCH_PER_CHIP, 32)

        class Ref(tnn.Module):
            def __init__(self):
                super().__init__()
                self.src_emb = tnn.Embedding(SRC_VOCAB, d)
                self.trg_emb = tnn.Embedding(TRG_VOCAB, d)
                self.core = tnn.Transformer(
                    d_model=d, nhead=8, num_encoder_layers=1,
                    num_decoder_layers=1, dim_feedforward=1024,
                    dropout=0.1, batch_first=True,
                )
                self.head = tnn.Linear(d, TRG_VOCAB)

            def forward(self, src, trg):
                mask = tnn.Transformer.generate_square_subsequent_mask(trg.shape[1])
                return self.head(
                    self.core(self.src_emb(src), self.trg_emb(trg), tgt_mask=mask)
                )

        model = Ref()
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        loss_fn = tnn.CrossEntropyLoss(ignore_index=0)
        src = torch.randint(1, SRC_VOCAB, (batch, SEQ))
        trg = torch.randint(1, TRG_VOCAB, (batch, SEQ))

        def one_step():
            opt.zero_grad()
            logits = model(src, trg[:, :-1])
            loss = loss_fn(logits.reshape(-1, TRG_VOCAB), trg[:, 1:].reshape(-1))
            loss.backward()
            opt.step()

        one_step()  # warmup
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        dt = time.perf_counter() - t0
        tps = batch * SEQ * steps / dt
        log(f"torch-cpu baseline: {steps} steps in {dt:.3f}s → {tps:,.0f} tokens/sec")
        return tps
    except Exception as e:  # baked-in torch should work; degrade gracefully
        log(f"torch baseline unavailable: {e!r}")
        return None


def main() -> None:
    value = bench_jax()
    baseline = bench_torch_baseline()
    vs = value / baseline if baseline else 1.0
    print(json.dumps({
        "metric": "transformer_mt_train_throughput",
        "value": round(value, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
