#!/usr/bin/env python
"""Benchmark — flagship Transformer MT workload + CNN, per-chip throughput.

Protocol per BASELINE.md: the reference publishes no numbers; its contract is
self-timed training throughput (``pytorch_machine_translator.py:199-205``
times batches of 32 × 200-token sentences; ``pytorch_cnn.py:123,148-151``
times the CNN epoch loop). Here the same workloads (reference hypers) run as
data-parallel jitted train steps in bfloat16, and ``vs_baseline`` is the
ratio against the reference-equivalent PyTorch model (same shapes, Adam/SGD)
measured on CPU in-process — the reference's own engine on the hardware it
targets (CPU-only end to end, SURVEY.md §3 observation b).

Aggregation policy: the headline ``value`` is the MEDIAN of ``TRIALS``
timing windows (the tunneled dev chip is shared, so single windows can be
skewed in either direction by neighbor noise); ``max``, the full trial list,
and the max/min ``spread`` are reported alongside so an outlier is visible,
not hidden. ``mfu`` is analytic matmul/conv FLOPs per train step (fwd + 2×
bwd) over the device's peak bf16 FLOP/s, computed at the median.

TPU measurement protocol (see PARITY.md "tunnel sync overhead"): 60-step
warmup past the chip/tunnel ramp; windows at N and 4N steps, headline from
the long window, with a paired-window difference estimate
(``paired_window``) that cancels the fixed ~0.1-0.25 s/trial sync cost; a
``scanned`` sub-result measuring the same MT workload through
``fit(steps_per_call=K)``'s fused-scan dispatch path; every device
workload under a deadline (wedged tunnel RPCs get abandoned, never block
the artifact), with hard failures retried once when transient.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "median": N, "max": N, "trials": [...], "spread": N, "mfu": N,
   "device": ..., "scanned": {...}, "packed": {...}, "composed": {...},
   "sweep": [...], "cnn": {"value": N, "unit": "samples/sec/chip", ...}}

Never exits non-zero for a measurement failure: any error is reported inside
the JSON (``"error"``) with value 0, so the artifact always parses.

Evidence contract: when the live backend is a CPU fallback (dead tunnel at
driver time), the artifact embeds ``tpu_evidence`` — the newest committed
on-chip record (``TPU_EVIDENCE.json``, capture-dated) — so the artifact of
record always carries a TPU number. On-chip runs refresh that record.
"""

from __future__ import annotations

import functools
import json
import os
import statistics
import sys
import time
import traceback

SEQ = 200
BATCH_PER_CHIP = int(os.environ.get("BENCH_BATCH", "32"))
SRC_VOCAB = 8192
TRG_VOCAB = 10240
D_MODEL, FFN, HEADS, LAYERS = 512, 1024, 8, 1
WARMUP = int(os.environ.get("BENCH_WARMUP", "5"))
# On TPU the chip+tunnel ramp for ~100+ steps before reaching steady state
# (r04 headline trials climbed monotonically 133K→224K tok/s); a longer
# warmup puts every measured window past the ramp. Per-backend env var
# (BENCH_TPU_*) wins over the generic one, which wins over the default.
def _env_int(specific: str, generic: str, default: int) -> int:
    return int(os.environ.get(specific, os.environ.get(generic, default)))


TPU_WARMUP = _env_int("BENCH_TPU_WARMUP", "BENCH_WARMUP", 60)
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
# TPU windows must dwarf the ~0.08-0.2s per-trial sync: the MT step is
# ~8.4ms on a v5e (60 steps ≈ 0.5s short window, 240-step long window ≈ 2s
# → sync < 10% of the long window); the CNN step is ~0.65ms, needing ~500.
TPU_STEPS = _env_int("BENCH_TPU_STEPS", "BENCH_STEPS", 60)
TPU_CNN_STEPS = _env_int("BENCH_TPU_CNN_STEPS", "BENCH_CNN_STEPS", 500)
TRIALS = int(os.environ.get("BENCH_TRIALS", "10"))
# Long-window multiplier for the TPU paired-window protocol (see
# _paired_window_stats): windows of STEPS and LONG_WINDOW×STEPS are both
# measured; their difference cancels the fixed per-trial sync cost.
LONG_WINDOW = int(os.environ.get("BENCH_LONG_WINDOW", "4"))
CNN_BATCH_PER_CHIP = int(os.environ.get("BENCH_CNN_BATCH", "512"))
CNN_STEPS = int(os.environ.get("BENCH_CNN_STEPS", "20"))
CNN_TRIALS = int(os.environ.get("BENCH_CNN_TRIALS", "5"))

# Peak dense bf16 FLOP/s per chip by TPU generation (public spec sheets).
_PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _probe_default_backend(timeout_s: float) -> bool:
    """Can the default backend initialize within ``timeout_s``?

    Probed in a SUBPROCESS because a dead TPU tunnel makes ``jax.devices()``
    hang (not raise) — and once the main process blocks inside backend init
    there is no recovery. A hung probe is killed and we fall back to CPU
    before this process ever touches the backend.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            # Fast failure is a different diagnosis than a hang: surface the
            # child's actual traceback so triage chases the real error.
            log(
                f"backend probe exited rc={proc.returncode}; stderr tail:\n"
                + "\n".join(proc.stderr.strip().splitlines()[-5:])
            )
            return False
        return True
    except subprocess.TimeoutExpired:
        log(f"backend probe hung past {timeout_s}s (dead tunnel?)")
        return False
    except Exception as e:
        log(f"backend probe failed to launch ({e!r}); assuming usable")
        return True


def _init_backend():
    """Initialize JAX, falling back to CPU if the default backend is broken
    or hung — a bench that crashes or hangs produces no artifact at all.
    """
    import jax

    # Unconditionally, before anything can log: the bench's stdout is ONE
    # machine-parsed JSON line, but package loggers default to stdout (the
    # examples' print-vocabulary parity) — a stray per-epoch or cache log
    # line on stdout would corrupt the driver-parsed artifact.
    try:
        from machine_learning_apache_spark_tpu.utils.logging import (
            route_logging_to_stderr,
        )

        route_logging_to_stderr()
    except Exception as e:
        log(f"logging reroute unavailable: {e!r}")
    _enable_compile_cache()
    if os.environ.get("BENCH_PLATFORM"):  # e.g. "cpu" for smoke runs
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    else:
        # The tunneled dev chip comes and goes: retry the probe a few times
        # (fresh subprocess each attempt) before surrendering to CPU, so a
        # transient outage at probe time doesn't cost the round's only TPU
        # measurement. Worst case is retries × timeout before fallback.
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "150"))
        retries = max(int(os.environ.get("BENCH_PROBE_RETRIES", "2")), 1)
        for attempt in range(retries):
            if _probe_default_backend(probe_timeout):
                break
            log(f"backend probe attempt {attempt + 1}/{retries} failed")
            if attempt < retries - 1:  # no pointless sleep before fallback
                time.sleep(min(10.0 * (attempt + 1), 30.0))
        else:
            log("default backend unusable (see probe log); falling back to CPU")
            jax.config.update("jax_platforms", "cpu")
    try:
        jax.devices()
    except Exception as e:
        log(f"default backend failed ({e!r}); falling back to CPU")
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        jax.devices()
    return jax


def _enable_compile_cache() -> None:
    """Persist XLA compiles across bench processes (BENCH_COMPILE_CACHE=0
    disables; BENCH_COMPILE_CACHE=<dir> relocates). Tunneled compiles cost
    20-60s per program — a warm cache turns a rerun's warmup into seconds."""
    val = os.environ.get("BENCH_COMPILE_CACHE", "")
    if val == "0":
        return
    try:
        from machine_learning_apache_spark_tpu.utils.compilation_cache import (
            enable_compilation_cache,
        )

        enable_compilation_cache(
            val or os.path.join(os.path.dirname(__file__), ".xla_cache")
        )
    except Exception as e:  # cache is an accelerant, never a dependency
        log(f"compilation cache unavailable: {e!r}")


def _peak_flops(device) -> float | None:
    if device.platform != "tpu":
        return None
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return 197e12  # conservative default for unrecognized TPU generations


def transformer_train_flops_per_step(
    batch: int, src_len: int, trg_len: int, layers: int = LAYERS
) -> float:
    """Analytic matmul FLOPs for one train step (fwd + 2× bwd ≈ 3× fwd).

    Counts only MXU work (projections, attention score/value matmuls, FFN,
    logits head); embedding lookups and softmax are excluded. Matches the
    reference architecture (d_model=512, ffn=1024, heads=8, 1 layer,
    ``pytorch_machine_translator.py:108-117``).
    """
    d, f = D_MODEL, FFN
    s, t = src_len, trg_len
    enc = layers * (4 * 2 * s * d * d + 2 * 2 * s * s * d + 2 * 2 * s * d * f)
    dec_self = 4 * 2 * t * d * d + 2 * 2 * t * t * d
    dec_cross = 2 * 2 * t * d * d + 2 * 2 * s * d * d + 2 * 2 * t * s * d
    dec_ffn = 2 * 2 * t * d * f
    dec = layers * (dec_self + dec_cross + dec_ffn)
    head = 2 * t * d * TRG_VOCAB
    return 3.0 * batch * (enc + dec + head)


def cnn_train_flops_per_step(batch: int, hw: int = 28, hidden: int = 10) -> float:
    """Analytic conv+dense FLOPs for one TinyVGG train step (3× fwd)."""
    fwd = 0.0
    h, c_in = hw, 1
    for _block in range(2):
        for _conv in range(2):
            fwd += 2 * 9 * c_in * hidden * h * h
            c_in = hidden
        h //= 2
    fwd += 2 * (hidden * h * h) * 10  # classifier head
    return 3.0 * batch * fwd


def _time_trials(step_fn, n_trials: int, n_steps: int, ready_fn) -> list[float]:
    """Per-trial wall-clock seconds for ``n_steps`` fully-materialized steps."""
    times = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            step_fn()
        ready_fn()
        times.append(time.perf_counter() - t0)
    return times


def _paired_window_stats(
    times_short: list[float],
    times_long: list[float],
    steps_short: int,
    steps_long: int,
    tokens_per_step: float,
) -> dict:
    """Cancel the fixed per-trial sync cost with two window lengths.

    The completion barrier is a device→host scalar fetch that costs one
    tunnel round-trip (~77 ms measured) plus queue drain — a *fixed* cost
    per trial that inflates short windows: the r04 session measured the
    same bs=32 config at 230K tok/s with 20-step windows and 429K with
    60-step windows. Timing windows of N and kN steps and differencing the
    medians solves for the per-step time with the constant eliminated:

        step_time = (median(T_long) - median(T_short)) / (kN - N)

    Returns the steady-state rate estimate and the implied per-trial
    overhead, both diagnostics alongside the directly-measured medians.
    """
    dt_s = statistics.median(times_short)
    dt_l = statistics.median(times_long)
    dstep = (dt_l - dt_s) / (steps_long - steps_short)
    if dstep <= 0:
        return {}  # noise exceeded the signal; nothing defensible to report
    overhead = dt_s - steps_short * dstep
    return {
        "steady_state_per_step_s": round(dstep, 6),
        "steady_state_rate": round(tokens_per_step / dstep, 1),
        "sync_overhead_s_per_trial": round(max(overhead, 0.0), 4),
    }


class MeasurementInvalid(RuntimeError):
    """A deliberate validity failure (e.g. MFU > 1 proves the timing barrier
    was defeated) — never retried; re-measuring can't fix a broken protocol.
    A dedicated type because JAX's own XlaRuntimeError subclasses
    RuntimeError, so matching RuntimeError would misclassify transient
    tunnel RPC failures as fatal."""


class _BudgetExhausted(Exception):
    """The total-run ledger ran out between retry attempts — never retried
    (waiting cannot create budget), reported as a skip, not a failure."""


def _with_deadline(fn, seconds: float, label: str):
    """Run a device workload with a wall-clock deadline.

    The tunnel has two distinct failure modes: RPCs that fail fast (handled
    by _transient_retry) and RPCs that hang forever — a mid-r04 sweep
    compile stalled 27+ minutes with the process otherwise healthy. A hung
    call cannot be cancelled, but it CAN be abandoned: the workload runs in
    a daemon thread, and on deadline the main thread moves on so the final
    JSON artifact always prints (a partial artifact beats none — the
    lesson of BENCH_r01/r03). The wedged thread dies with the process.
    """
    import threading

    box: dict = {}

    def run():
        try:
            box["result"] = fn()
        except Exception as e:  # noqa: BLE001 — reported via the artifact
            box["error"] = e

    t = threading.Thread(target=run, daemon=True, name=f"bench-{label}")
    t.start()
    t.join(seconds)
    if t.is_alive():
        log(f"{label} exceeded its {seconds:.0f}s deadline (hung tunnel "
            f"RPC?) — abandoning the thread and moving on")
        raise TimeoutError(f"{label} deadline ({seconds:.0f}s) exceeded")
    if "error" in box:
        raise box["error"]
    return box["result"]


def _sweep_point_cmd(bpc: int, layers: int) -> list[str]:
    """Argv for one isolated sweep point — module-level so tests can swap
    in a stub child."""
    return [
        sys.executable, os.path.abspath(__file__),
        "--sweep-point", f"{bpc}x{layers}",
    ]


def _run_point_isolated(bpc: int, layers: int, deadline: float) -> dict:
    """Run one sweep point in its OWN process under a hard timeout.

    The r05 artifact ended in ``{"truncated": "hung point"}``: a compile
    wedged inside ``_with_deadline`` can only be *abandoned*, and the
    orphan thread still owns the chip once its RPC un-wedges, so the
    in-process sweep has no choice but to quarantine after one hang. A
    subprocess dies WITH its wedged compile (killpg on timeout), leaving
    the chip free — one hang costs one ``{"error": ...}`` row and the
    sweep continues to the next point instead of truncating the artifact.
    """
    import subprocess

    proc = subprocess.Popen(
        _sweep_point_cmd(bpc, layers),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,  # killpg must reach the child's own spawns
    )
    try:
        out, err = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.communicate()
        raise TimeoutError(
            f"sweep point bs={bpc} L={layers} deadline "
            f"({deadline:.0f}s) exceeded; child killed"
        ) from None
    if proc.returncode != 0:
        tail = " | ".join((err or out or "").strip().splitlines()[-5:])
        raise RuntimeError(
            f"sweep point bs={bpc} L={layers} exited {proc.returncode}: {tail}"
        )
    lines = [ln for ln in (out or "").splitlines() if ln.strip()]
    if not lines:
        raise RuntimeError(f"sweep point bs={bpc} L={layers}: no output")
    return json.loads(lines[-1])


def _sweep_point_main(token: str) -> int:
    """Child mode for ``_run_point_isolated``: run ONE sweep point and
    print its ``bench_transformer`` dict as the last stdout line. Backend
    init follows the same probe/fallback path as ``main()`` (so
    ``BENCH_PLATFORM=cpu`` smoke children stay on CPU)."""
    b, layers = token.strip().lower().split("x")
    jax = _init_backend()
    _degraded_mode_knobs(jax)
    r = bench_transformer(
        jax, batch_per_chip=int(b), layers=int(layers),
        trials=2, steps=10, warmup=5,
    )
    print(json.dumps(r))
    return 0


def _transient_retry(fn, label: str, attempts: int = 2):
    """Retry a bench workload once after a transient tunnel RPC failure.

    The tunneled dev chip drops RPCs sporadically (`remote_compile: read
    body: response body closed` killed a mid-session r04 run); one retry
    after a pause recovers it because the jit cache survives in-process.
    """
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:
            # TimeoutError is fatal too: the abandoned thread may still be
            # executing on the device — a retry would interleave two
            # workloads and report contention-corrupted timings.
            fatal = attempt == attempts - 1 or isinstance(
                e, (MeasurementInvalid, TimeoutError, _BudgetExhausted)
            )
            if fatal:
                raise
            log(f"{label} attempt {attempt + 1} failed transiently: {e!r}; "
                f"retrying in 15s")
            time.sleep(15)


def _value_barrier(holder) -> float:
    """Completion barrier that an async dispatch layer cannot satisfy early:
    transfer the trial's final loss scalar AND one element of an updated
    param to the host. Those bytes depend on the whole step chain (the loss
    on the last forward over 19 prior updates, the param element on the last
    optimizer update), so the fetch cannot return before every dispatched
    step has actually executed.

    Why not ``jax.block_until_ready``: under the tunneled dev-chip relay it
    has been observed returning after *enqueue*, not completion — producing
    physically impossible rates (BENCH_r02's 4.2M tok/s/chip; a first r04
    run printed 73M tok/s/chip ≈ 2468% MFU on the same workload). A literal
    value fetch is the only barrier whose result proves execution happened.
    Costs one scalar-RPC round-trip per *trial* (not per step) — noise at
    multi-step trial granularity.
    """
    import jax

    leaf = jax.tree.leaves(holder["state"].params)[0]
    # holder["loss"] exists only after the first step (warmup may be 0).
    loss = float(holder["loss"]) if "loss" in holder else 0.0
    return float(leaf.ravel()[0]) + loss


def _check_mfu(achieved: float, peak: float | None, label: str) -> float | None:
    """Reject physically impossible rates instead of reporting them."""
    if not peak:
        return None
    mfu = achieved / peak
    if mfu > 1.0:
        # A rate above the chip's peak proves the barrier was defeated (or
        # the clock/FLOP model is broken) — never report it as a result.
        raise MeasurementInvalid(
            f"measured {label} MFU {mfu:.2f} exceeds 1.0 — timing barrier "
            f"defeated (async-ack relay?); measurement invalid"
        )
    return mfu


_EVIDENCE_PATH = os.path.join(os.path.dirname(__file__), "TPU_EVIDENCE.json")


def _load_tpu_evidence() -> dict | None:
    """Newest committed on-chip record, for embedding when the live backend
    is a CPU fallback. The driver artifact has read "cpu" whenever the
    tunnel happened to be dead at end-of-round (4/4 rounds), while the real
    TPU measurements sat in separately committed BENCH_SELF_* files — this
    puts them in the artifact of record, clearly labeled with capture date.
    """
    try:
        with open(_EVIDENCE_PATH) as f:
            return json.load(f)
    except Exception as e:
        log(f"no committed TPU evidence available: {e!r}")
        return None


def _record_tpu_evidence(result: dict) -> None:
    """After a successful on-chip run, refresh TPU_EVIDENCE.json so future
    CPU-fallback artifacts embed the newest numbers. MERGES into the
    existing record: only stages that actually measured this run overwrite
    their keys, so a partial run (e.g. CNN errored) never erases the last
    good number for the other workloads. Best-effort: a read-only checkout
    must not fail the bench."""
    ev: dict = _load_tpu_evidence() or {}
    ev.update({
        "captured": time.strftime("%Y-%m-%d"),
        "round": os.environ.get("BENCH_ROUND", "self"),
        "note": (
            "Curated record of the newest committed on-chip measurements; "
            "embedded as 'tpu_evidence' in CPU-fallback artifacts. "
            "Auto-refreshed (merge per stage) by bench.py after a "
            "successful on-chip run; per-stage capture dates in "
            "'stage_captured'."
        ),
    })
    stamped: list[str] = []
    if result.get("median") and not result.get("error"):
        stamped.append("transformer")
        ev["transformer"] = {
            "median_tokens_per_sec_chip": result["median"],
            "mfu": result.get("mfu"),
            "spread": result.get("spread"),
            "batch_per_chip": result.get("batch_per_chip"),
            "layers": result.get("layers"),
            "seq": SEQ,
            "protocol": (
                f"warmup={TPU_WARMUP}, {TRIALS} trials x "
                f"{result.get('steps_per_trial')}-step synced windows, "
                "value-fetch barrier"
            ),
            "source": "bench.py on-chip run",
        }
        pw = result.get("paired_window")
        if pw:
            ev["transformer"]["paired_window_steady_state"] = {
                "tokens_per_sec_chip": pw.get("steady_state_rate"),
                "mfu": pw.get("steady_state_mfu"),
            }
    for key in ("scanned", "packed", "composed", "sweep"):
        if key == "sweep":
            # Per-(batch, layers) merge: only the rows that measured
            # cleanly bank; error/truncated rows from a hang cost that
            # point, never the rows that landed — neither this run's nor
            # an earlier window's (a BENCH_SWEEP_POINTS re-capture of the
            # stolen points must not re-measure the survivors).
            rows = [
                p for p in result.get("sweep") or []
                if isinstance(p, dict)
                and "error" not in p and "truncated" not in p
            ]
            if not rows:
                continue
            stamped.append(key)
            merged = {
                (p.get("batch_per_chip"), p.get("layers")): p
                for p in (ev.get(key) or [])
                if isinstance(p, dict)
                and "error" not in p and "truncated" not in p
            }
            merged.update({
                (p.get("batch_per_chip"), p.get("layers")): p for p in rows
            })
            ev[key] = sorted(
                merged.values(),
                key=lambda p: (p.get("layers") or 0,
                               p.get("batch_per_chip") or 0),
            )
        elif result.get(key) and not (
            isinstance(result[key], dict)
            and (result[key].get("error") or result[key].get("skipped"))
        ):
            stamped.append(key)
            ev[key] = result[key]
    cnn = result.get("cnn")
    if isinstance(cnn, dict) and cnn.get("median") and not cnn.get("error"):
        stamped.append("cnn_scanned")
        ev["cnn_scanned"] = {
            "median_samples_per_sec_chip": cnn["median"],
            "spread": cnn.get("spread"),
            "scan_k": cnn.get("scan_k"),
            "mfu": cnn.get("mfu"),
            "batch_per_chip": cnn.get("batch_per_chip"),
            "source": "bench.py on-chip run",
        }
    if not stamped:
        return  # nothing measured on chip this run; keep the old record
    dates = dict(ev.get("stage_captured") or {})
    dates.update({k: ev["captured"] for k in stamped})
    ev["stage_captured"] = dates
    try:
        # Atomic replace: a SIGTERM mid-write (the watcher wraps bench.py
        # in `timeout`) must not truncate the one record the whole
        # evidence contract depends on.
        tmp = _EVIDENCE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ev, f, indent=2)
            f.write("\n")
        os.replace(tmp, _EVIDENCE_PATH)
        log(f"TPU evidence record refreshed at {_EVIDENCE_PATH} "
            f"(stages: {', '.join(stamped)})")
    except Exception as e:
        log(f"could not refresh TPU evidence record: {e!r}")


def _tpu_stages(jax) -> bool:
    """Gate for the TPU-only stages (scanned/packed/sweep) in main().

    BENCH_FORCE_TPU_STAGES=1 opens the gate on any backend — a smoke hook
    so the stage GLUE (retry/deadline wrappers, result merging) can be
    executed on CPU with tiny plans; without it, glue bugs would first
    surface on the driver's end-of-round TPU run.
    """
    if os.environ.get("BENCH_FORCE_TPU_STAGES", "") not in ("", "0"):
        return True
    return jax.devices()[0].platform == "tpu"


def _degraded_mode_knobs(jax) -> None:
    """On a CPU fallback, shrink the measurement plan so the artifact lands
    within the driver's window: CPU steps are ~100× slower than the chip's,
    and a full 10×20-step schedule there can outlast the bench timeout —
    producing NO artifact instead of a degraded one. Explicit env settings
    always win."""
    if jax.devices()[0].platform == "tpu":
        return
    # 10-step windows (not 5): on ~8s/step CPU a 5-step window judges the
    # jax-vs-torch ratio on luck-of-the-draw noise; 10 steps halves the
    # relative jitter while keeping the whole degraded plan within the
    # driver's window (~4 min transformer + ~1 min torch baseline).
    defaults = {
        "BENCH_TRIALS": ("TRIALS", 3),
        "BENCH_STEPS": ("STEPS", 10),
        "BENCH_CNN_TRIALS": ("CNN_TRIALS", 2),
        "BENCH_CNN_STEPS": ("CNN_STEPS", 10),
        "BENCH_WARMUP": ("WARMUP", 2),
    }
    for env, (name, value) in defaults.items():
        if env not in os.environ:
            globals()[name] = value
    log(
        f"non-TPU backend: degraded measurement plan "
        f"(trials={TRIALS}×{STEPS} steps, cnn {CNN_TRIALS}×{CNN_STEPS})"
    )


def bench_transformer(
    jax,
    *,
    batch_per_chip: int | None = None,
    layers: int = LAYERS,
    trials: int | None = None,
    steps: int | None = None,
    warmup: int | None = None,
    scan_k: int = 1,
    seq: int | None = None,
) -> dict:
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from machine_learning_apache_spark_tpu.models import (
        Transformer,
        TransformerConfig,
    )
    from machine_learning_apache_spark_tpu.parallel import (
        DATA_AXIS,
        make_mesh,
        shard_params,
    )
    from machine_learning_apache_spark_tpu.train.losses import (
        masked_token_cross_entropy,
    )
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    t_setup = time.perf_counter()
    batch_per_chip = BATCH_PER_CHIP if batch_per_chip is None else batch_per_chip
    seq = SEQ if seq is None else seq
    trials = TRIALS if trials is None else trials
    n_chips = jax.device_count()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if steps is None:
        steps = TPU_STEPS if on_tpu else STEPS
    if warmup is None:
        warmup = TPU_WARMUP if on_tpu else WARMUP
    cfg = TransformerConfig(
        src_vocab_size=SRC_VOCAB,
        trg_vocab_size=TRG_VOCAB,
        max_len=seq,
        num_layers=layers,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )
    model = Transformer(cfg)
    mesh = make_mesh({DATA_AXIS: n_chips})
    batch = batch_per_chip * n_chips

    # Several distinct batches, rotated per step: reusing one batch would
    # invite (unfounded but unfalsifiable) work-elision doubts about the
    # measurement; rotation costs nothing and removes the hypothesis.
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    n_batches = 4
    batches = []
    for i in range(n_batches):
        rng = jax.random.key(i)
        src = jax.random.randint(rng, (batch, seq), 1, SRC_VOCAB, dtype=jnp.int32)
        trg = jax.random.randint(rng, (batch, seq), 1, TRG_VOCAB, dtype=jnp.int32)
        batches.append(
            (jax.device_put(src, sharding), jax.device_put(trg, sharding))
        )
    src, trg = batches[0]

    params = shard_params(
        model.init(jax.random.key(1), src[:2], trg[:2])["params"], mesh
    )
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("adam", 1e-3)
    )

    def loss_fn(params, src, trg, rng):
        logits = model.apply(
            {"params": params},
            src,
            trg[:, :-1],
            deterministic=False,
            rngs={"dropout": rng},
        )
        return masked_token_cross_entropy(logits, trg[:, 1:], cfg.pad_id)

    # Donated state: in-place param/opt updates, no copy — HBM-traffic win.
    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, src, trg, rng):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, src, trg, rng)
        return state.apply_gradients(grads), loss

    holder = {"state": state, "rng": jax.random.key(2), "i": 0}

    if scan_k > 1:
        # The scanned product path (train.loop.make_multi_step /
        # fit(steps_per_call=K)): K steps per dispatch. The distinct
        # batches rotate INSIDE the stack (cycled to length K); across
        # dispatches the same stack is replayed — unlike the per-step
        # path's endless rotation, but each step in a window still sees
        # the same input variety.
        import numpy as np
        from machine_learning_apache_spark_tpu.parallel import (
            shard_batch_stack,
        )
        from machine_learning_apache_spark_tpu.train.loop import (
            make_multi_step,
        )

        def scan_loss(params, b, rng):
            return loss_fn(params, b[0], b[1], rng), {}

        multi = make_multi_step(scan_loss)
        host = [
            (np.asarray(s), np.asarray(t))
            for s, t in batches[: min(n_batches, scan_k)]
        ]
        stacked = shard_batch_stack(
            mesh, [host[i % len(host)] for i in range(scan_k)]
        )

        def one_step():
            holder["state"], holder["rng"], losses, _ = multi(
                holder["state"], stacked, holder["rng"]
            )
            holder["loss"] = losses[-1]
    else:

        def one_step():
            holder["rng"], sub = jax.random.split(holder["rng"])
            s, t = batches[holder["i"] % n_batches]
            holder["i"] += 1
            holder["state"], holder["loss"] = step(holder["state"], s, t, sub)

    for _ in range(warmup):
        one_step()
    _value_barrier(holder)
    # Setup + compile + warmup wall time: the persistent compile cache's
    # effect shows here — two fresh-process runs of the same program
    # differ by the compile time the cache absorbed (VERDICT r04 item 5's
    # measured before/after).
    setup_s = time.perf_counter() - t_setup
    loss0 = float(holder["loss"]) if "loss" in holder else float("nan")
    log(
        f"jax transformer warmup done on {n_chips} × {device.platform} "
        f"(bs/chip={batch_per_chip}, layers={layers}, loss={loss0:.3f}, "
        f"setup+warmup {setup_s:.1f}s)"
    )

    if os.environ.get("BENCH_PROFILE_DIR"):
        # Device trace of a few steady-state steps — the ground truth for
        # reconciling measured throughput against analytic FLOPs (MFU).
        with jax.profiler.trace(os.environ["BENCH_PROFILE_DIR"]):
            for _ in range(5):
                one_step()
            _value_barrier(holder)
        log(f"profiler trace written to {os.environ['BENCH_PROFILE_DIR']}")

    barrier = lambda: _value_barrier(holder)  # noqa: E731
    times = _time_trials(one_step, trials, steps, barrier)
    for t, dt in enumerate(times):
        r = batch * seq * steps * scan_k / dt / n_chips
        log(f"jax trial {t}: {steps * scan_k} steps in {dt:.3f}s → "
            f"{r:,.0f} tokens/sec/chip")
    paired = {}
    head_steps, head_times = steps * scan_k, times
    if on_tpu and LONG_WINDOW > 1:
        # Long windows amortize the fixed per-trial sync round-trip; the
        # headline is the directly-measured long-window median, and the
        # short/long pair yields the sync-free steady-state diagnostic.
        steps_long = steps * LONG_WINDOW
        times_long = _time_trials(one_step, trials, steps_long, barrier)
        for t, dt in enumerate(times_long):
            r = batch * seq * steps_long * scan_k / dt / n_chips
            log(f"jax long trial {t}: {steps_long * scan_k} steps in "
                f"{dt:.3f}s → {r:,.0f} tokens/sec/chip")
        paired = _paired_window_stats(
            times, times_long, steps * scan_k, steps_long * scan_k,
            batch * seq / n_chips,
        )
        head_steps, head_times = steps_long * scan_k, times_long
    tps = sorted(batch * seq * head_steps / dt / n_chips for dt in head_times)
    median = statistics.median(tps)
    flops_step = transformer_train_flops_per_step(batch, seq, seq - 1, layers)
    peak = _peak_flops(device)
    median_dt = statistics.median(head_times)
    achieved = flops_step * head_steps / median_dt / n_chips
    mfu = _check_mfu(achieved, peak, "transformer")
    out = {
        "median": round(median, 1),
        "max": round(tps[-1], 1),
        "trials": [round(x, 1) for x in tps],
        "spread": round(tps[-1] / tps[0], 2) if tps[0] else None,
        "steps_per_trial": head_steps,
        "scan_k": scan_k,
        "flops_per_step": flops_step,
        "achieved_flops_per_sec_chip": round(achieved, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device": getattr(device, "device_kind", device.platform),
        "n_chips": n_chips,
        "batch_per_chip": batch_per_chip,
        "layers": layers,
        "loss": round(float(holder["loss"]), 3),
        "setup_plus_warmup_s": round(setup_s, 1),
    }
    if paired:
        # MFU at the sync-free steady-state rate (diagnostic, not headline).
        steady_mfu = (
            flops_step / (batch * seq) * paired["steady_state_rate"] / peak
            if peak else None
        )
        if steady_mfu is not None and steady_mfu > 1.0:
            log("paired-window estimate exceeds chip peak — differencing "
                "noise, discarding the diagnostic")
        else:
            paired["steady_state_mfu"] = (
                round(steady_mfu, 4) if steady_mfu is not None else None
            )
            out["paired_window"] = paired
    return out


def _synthetic_packed_corpus(n_pairs: int):
    """Multi30k-shaped ragged pairs (clipped-normal lengths, mean ~15 src /
    ~17 trg vs the reference's fixed 200-token rows,
    ``pytorch_machine_translator.py:70-98``), packed to the bench grid.
    Shared by the packed and composed stages so their pairs/sec numbers
    measure the same corpus distribution."""
    import numpy as np

    from machine_learning_apache_spark_tpu.data.packing import (
        pack_translation_pairs,
    )

    rng = np.random.default_rng(0)

    def ragged(n, vocab, mean):
        lens = np.clip(rng.normal(mean, 5.0, n), 4, 60).astype(int)
        return [list(rng.integers(4, vocab, l)) for l in lens]

    return pack_translation_pairs(
        ragged(n_pairs, SRC_VOCAB, 15.0), ragged(n_pairs, TRG_VOCAB, 17.0),
        src_len=SEQ, trg_len=SEQ,
    )


def bench_packed_transformer(
    jax, *, trials: int = 3, steps: int = 10, warmup: int = 10
) -> dict:
    """Effective-throughput measurement of sequence packing on the MT
    workload (``pack_sequences=True``): synthetic ragged pairs with a
    Multi30k-like length distribution (mean ~15 tokens vs the fixed
    200-token rows of ``pytorch_machine_translator.py:70-98``), packed by
    ``data.packing`` and trained with the packed loss. The headline metric
    is PAIRS/sec/chip — the work a user actually cares about — which the
    fixed-width layout caps at (token rate)/200 regardless of how short
    the sentences are.
    """
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from machine_learning_apache_spark_tpu.models import (
        Transformer,
        TransformerConfig,
    )
    from machine_learning_apache_spark_tpu.parallel import DATA_AXIS, make_mesh
    from machine_learning_apache_spark_tpu.recipes.translation import (
        make_packed_translation_loss,
    )
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    n_chips = jax.device_count()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    batch = BATCH_PER_CHIP * n_chips
    packed = _synthetic_packed_corpus(4096)
    rows = len(packed.src)
    pairs_per_row = packed.pair_count / rows

    cfg = TransformerConfig(
        src_vocab_size=SRC_VOCAB,
        trg_vocab_size=TRG_VOCAB,
        max_len=SEQ,
        num_layers=LAYERS,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )
    model = Transformer(cfg)
    mesh = make_mesh({DATA_AXIS: n_chips})
    sharding = NamedSharding(mesh, P(DATA_AXIS))

    n_batches = 4
    batches = []
    for i in range(n_batches):
        idx = (np.arange(batch) + i * batch) % rows
        batches.append(tuple(
            jax.device_put(jnp.asarray(a[idx]), sharding)
            for a in packed.arrays()
        ))

    params = model.init(
        jax.random.key(1), batches[0][0][:2], batches[0][3][:2, :-1]
    )["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("adam", 1e-3)
    )
    loss_fn = make_packed_translation_loss(model, cfg.pad_id)

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, b, rng):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, b, rng
        )
        return state.apply_gradients(grads), loss

    holder = {"state": state, "rng": jax.random.key(2), "i": 0}

    def one_step():
        holder["rng"], sub = jax.random.split(holder["rng"])
        b = batches[holder["i"] % n_batches]
        holder["i"] += 1
        holder["state"], holder["loss"] = step(holder["state"], b, sub)

    for _ in range(warmup):
        one_step()
    _value_barrier(holder)
    log(f"packed warmup done ({pairs_per_row:.1f} pairs/row, "
        f"grid use {packed.token_efficiency:.1%})")

    barrier = lambda: _value_barrier(holder)  # noqa: E731
    if on_tpu and LONG_WINDOW > 1:
        # Long windows only: this bench reports one rate (no paired-window
        # diagnostic), so a short-window pass would be discarded work.
        steps = steps * LONG_WINDOW
    times = _time_trials(one_step, trials, steps, barrier)
    pairs_rate = sorted(
        batch * pairs_per_row * steps / dt / n_chips for dt in times
    )
    median = statistics.median(pairs_rate)
    for dt in times:
        log(f"packed: {steps} steps in {dt:.3f}s → "
            f"{batch * pairs_per_row * steps / dt / n_chips:,.0f} pairs/sec/chip")
    return {
        "pairs_per_sec_chip": round(median, 1),
        "max": round(pairs_rate[-1], 1),
        "spread": round(pairs_rate[-1] / pairs_rate[0], 2),
        "pairs_per_row": round(pairs_per_row, 2),
        "token_efficiency": round(packed.token_efficiency, 4),
        "unpacked_token_efficiency": round(packed.unpacked_efficiency, 4),
        "loss": round(float(holder["loss"]), 3),
    }


def bench_composed(
    jax,
    *,
    batch_per_chip: int = 512,
    scan_k: int = 4,
    trials: int = 4,
    steps: int = 5,
    warmup_dispatches: int = 25,
    n_pairs: int = 65536,
) -> dict:
    """Best-achievable record: the three throughput levers COMPOSED on the
    reference MT model — sequence packing (input density: ~11-12 pairs per
    200-token row instead of 1), scanned dispatch (``fit(steps_per_call=K)``
    semantics: K steps per host RPC), and a large batch (MXU tiling +
    fixed-cost amortization; see TPU_ROOFLINE.md). This is the config
    a real user of the framework would run the reference's Multi30k workload
    at (``pytorch_machine_translator.py:199-205`` contract); the headline
    stages keep the reference's own bs=32 per-step shape for comparability,
    this one records what the framework actually achieves.

    Reported: pairs/sec/chip (the user-meaningful rate), the grid token
    rate and its MFU (what the chip computes, pad included), and the
    effective non-pad token rate.
    """
    import numpy as np
    import jax.numpy as jnp

    from machine_learning_apache_spark_tpu.models import (
        Transformer,
        TransformerConfig,
    )
    from machine_learning_apache_spark_tpu.parallel import (
        DATA_AXIS,
        make_mesh,
        shard_batch_stack,
    )
    from machine_learning_apache_spark_tpu.recipes.translation import (
        make_packed_translation_loss,
    )
    from machine_learning_apache_spark_tpu.train.loop import make_multi_step
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    n_chips = jax.device_count()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if not on_tpu:
        # The composed plan is sized for a v5e (~180 bs-512 steps). On a
        # CPU smoke run (BENCH_FORCE_TPU_STAGES) that would blow the stage
        # deadline and quarantine everything after it — shrink to a plan
        # that exercises the same code path in seconds.
        batch_per_chip = min(batch_per_chip, 4)
        scan_k = min(scan_k, 2)
        trials, steps, warmup_dispatches = 2, 2, 1
        n_pairs = min(n_pairs, 512)
    batch = batch_per_chip * n_chips
    # n_pairs default: enough distinct pairs that the scan stack's rows
    # don't repeat across the K stacked batches at bs=512.
    packed = _synthetic_packed_corpus(n_pairs)
    rows = len(packed.src)
    pairs_per_row = packed.pair_count / rows

    cfg = TransformerConfig(
        src_vocab_size=SRC_VOCAB,
        trg_vocab_size=TRG_VOCAB,
        max_len=SEQ,
        num_layers=LAYERS,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )
    model = Transformer(cfg)
    mesh = make_mesh({DATA_AXIS: n_chips})

    host_batches = []
    for i in range(scan_k):
        idx = (np.arange(batch) + i * batch) % rows
        host_batches.append(tuple(a[idx] for a in packed.arrays()))
    stacked = shard_batch_stack(mesh, host_batches)

    params = model.init(
        jax.random.key(1),
        jnp.asarray(packed.src[:2]),
        jnp.asarray(packed.trg[:2, :-1]),
    )["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("adam", 1e-3)
    )
    multi = make_multi_step(make_packed_translation_loss(model, cfg.pad_id))

    holder = {"state": state, "rng": jax.random.key(2)}

    def one_dispatch():
        holder["state"], holder["rng"], losses, _ = multi(
            holder["state"], stacked, holder["rng"]
        )
        holder["loss"] = losses[-1]

    for _ in range(warmup_dispatches):
        one_dispatch()
    _value_barrier(holder)
    log(
        f"composed warmup done (bs/chip={batch_per_chip}, scan_k={scan_k}, "
        f"{pairs_per_row:.1f} pairs/row, grid use "
        f"{packed.token_efficiency:.1%}, loss={float(holder['loss']):.3f})"
    )

    barrier = lambda: _value_barrier(holder)  # noqa: E731
    times = _time_trials(one_dispatch, trials, steps, barrier)
    real_steps = steps * scan_k
    pairs_rate = sorted(
        batch * pairs_per_row * real_steps / dt / n_chips for dt in times
    )
    for dt in times:
        log(f"composed: {real_steps} steps in {dt:.3f}s → "
            f"{batch * pairs_per_row * real_steps / dt / n_chips:,.0f} "
            f"pairs/sec/chip")
    median_pairs = statistics.median(pairs_rate)
    median_dt = statistics.median(times)
    grid_tokens = batch * SEQ * real_steps / median_dt / n_chips
    flops_step = transformer_train_flops_per_step(batch, SEQ, SEQ - 1, LAYERS)
    peak = _peak_flops(device)
    achieved = flops_step * real_steps / median_dt / n_chips
    mfu = _check_mfu(achieved, peak, "composed")
    return {
        "pairs_per_sec_chip": round(median_pairs, 1),
        "max": round(pairs_rate[-1], 1),
        "spread": round(pairs_rate[-1] / pairs_rate[0], 2),
        "grid_tokens_per_sec_chip": round(grid_tokens, 1),
        "effective_tokens_per_sec_chip": round(
            grid_tokens * packed.token_efficiency, 1
        ),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "batch_per_chip": batch_per_chip,
        "scan_k": scan_k,
        "steps_per_trial": real_steps,
        "pairs_per_row": round(pairs_per_row, 2),
        "token_efficiency": round(packed.token_efficiency, 4),
        "loss": round(float(holder["loss"]), 3),
    }


def bench_transformer_sweep(
    jax, points: list | None = None, stop_at: float | None = None
) -> list[dict]:
    """MFU scaling sweep: batch-per-chip {32, 128, 256} × layers {1, 4} on
    the MT workload. The reference config (bs=32, 1 layer, seq 200) is
    latency-bound and undersells the MXU; this locates where the framework
    actually peaks. TPU-only (CPU points would be minutes each and say
    nothing about the MXU). Fewer trials than the headline: the goal is an
    MFU-vs-config surface, not the headline number; the paired-window
    protocol inside bench_transformer still applies per point.

    ``points`` may be caller-supplied so completed points survive a
    deadline abandonment mid-sweep; ``stop_at`` (a ``time.monotonic()``
    timestamp) makes a healthy-but-slow sweep stop itself between points —
    the outer thread-abandon deadline is only the backstop for a single
    wedged call, never the scheduler for a live one (see main()).
    """
    points = [] if points is None else points
    point_deadline = float(os.environ.get("BENCH_SWEEP_POINT_DEADLINE", "300"))
    # Per-point process isolation (see _run_point_isolated): default ON
    # for a real chip — that's where compiles hang — and OFF on CPU, where
    # the in-process path is cheaper and tests monkeypatch
    # bench_transformer directly. BENCH_SWEEP_ISOLATE overrides both ways.
    iso_env = os.environ.get("BENCH_SWEEP_ISOLATE")
    if iso_env is not None:
        isolate = iso_env.strip().lower() not in ("", "0", "false", "no")
    else:
        try:
            isolate = jax.devices()[0].platform == "tpu"
        except Exception:
            isolate = False
    # BENCH_SWEEP_POINTS="32x4,128x4" makes the plan exactly those
    # (batch_per_chip x layers) points, in order — chip windows through the
    # tunnel are scarce, and a re-capture of points a hang stole must not
    # spend its window re-measuring the ones that already landed.
    only_env = os.environ.get("BENCH_SWEEP_POINTS", "").strip()
    if only_env:
        # Tolerant parse: a typo'd token must cost that token, not the
        # whole sweep stage of a scarce chip window.
        plan = []
        for tok in only_env.split(","):
            try:
                b, l = tok.strip().lower().split("x")
                plan.append((int(b), int(l)))
            except ValueError:
                if tok.strip():
                    log(f"BENCH_SWEEP_POINTS: skipping malformed {tok!r}")
    else:
        plan = [
            (bpc, layers)
            for layers in (1, 4)
            for bpc in (32, 128, 256, 512)
            # 512x4 is ~50s/trial; the surface is clear by then. The
            # headline config is already measured by its own stage.
            if not (layers == 4 and bpc == 512)
            and not (bpc == BATCH_PER_CHIP and layers == LAYERS)
        ]
    for bpc, layers in plan:
        if stop_at is not None and time.monotonic() >= stop_at:
            log("sweep stopped at its time budget; returning "
                f"{len(points)} completed points")
            # Sentinel: marks the list as incomplete so the evidence
            # recorder won't let it displace a complete committed sweep.
            points.append({"truncated": "time budget"})
            return points
        try:
            if isolate:
                r = _run_point_isolated(bpc, layers, point_deadline)
            else:
                r = _with_deadline(
                    lambda: bench_transformer(
                        jax, batch_per_chip=bpc, layers=layers,
                        trials=2, steps=10, warmup=5,
                    ),
                    point_deadline,
                    f"sweep bs={bpc} L={layers}",
                )
            points.append({
                "batch_per_chip": bpc,
                "layers": layers,
                "tokens_per_sec_chip": r["median"],
                "mfu": r["mfu"],
                "spread": r["spread"],
                "steady_state_mfu": r.get("paired_window", {}).get(
                    "steady_state_mfu"
                ),
            })
            log(
                f"sweep bs/chip={bpc} layers={layers}: "
                f"{r['median']:,.0f} tok/s/chip, mfu={r['mfu']}"
            )
        except Exception as e:
            log(f"sweep point bs={bpc} layers={layers} failed: {e!r}")
            if isolate:
                # The hung/broken compile died with its process; the chip
                # is free, so this point's failure is ITS failure alone —
                # record the casualty row and keep sweeping.
                points.append({
                    "batch_per_chip": bpc, "layers": layers,
                    "error": repr(e), "isolated": True,
                })
                continue
            points.append({
                "batch_per_chip": bpc, "layers": layers, "error": repr(e),
            })
            if isinstance(e, TimeoutError):
                # Single strike: the abandoned thread may STILL be
                # executing on the chip once its RPC un-wedges — any
                # further point would measure contention, not the
                # framework (same reasoning as _transient_retry's
                # fatal-TimeoutError rule).
                log("sweep quarantined after a hung point")
                points.append({"truncated": "hung point"})
                return points
    return points


def bench_cnn(jax) -> dict:
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from machine_learning_apache_spark_tpu.models import TinyVGG
    from machine_learning_apache_spark_tpu.parallel import DATA_AXIS, make_mesh
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    n_chips = jax.device_count()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    model = TinyVGG(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    mesh = make_mesh({DATA_AXIS: n_chips})
    batch = CNN_BATCH_PER_CHIP * n_chips

    rng = jax.random.key(0)
    x = jax.random.normal(rng, (batch, 28, 28, 1), dtype=jnp.float32)
    y = jax.random.randint(rng, (batch,), 0, 10, dtype=jnp.int32)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    x, y = jax.device_put(x, sharding), jax.device_put(y, sharding)

    params = model.init(jax.random.key(1), x[:2])["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.01)
    )

    def loss_fn(params, x, y):
        logits = model.apply({"params": params}, x)
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, x, y)
        return state.apply_gradients(grads), loss

    holder = {"state": state}

    # The TinyVGG step is ~0.65 ms on a v5e — per-step host dispatch (an RPC
    # on the tunneled topology, ~2.3 ms) caps it at ~30% of the chip. The
    # framework's answer is the scanned trainer (fit(steps_per_call=K) /
    # train.loop.make_multi_step): K steps fused into one dispatch. The
    # bench measures that product path; BENCH_CNN_SCAN=1 restores per-step
    # dispatch for comparison.
    scan_k = int(os.environ.get("BENCH_CNN_SCAN", "50")) if on_tpu else 1
    if scan_k > 1:
        import numpy as np
        from machine_learning_apache_spark_tpu.parallel import (
            shard_batch_stack,
        )
        from machine_learning_apache_spark_tpu.train.loop import (
            make_multi_step,
        )

        def scan_loss(params, b, rng):
            bx, by = b
            return loss_fn(params, bx, by), {}

        multi = make_multi_step(scan_loss)
        stacked = shard_batch_stack(mesh, [(np.asarray(x), np.asarray(y))] * scan_k)
        holder["rng"] = jax.random.key(2)

        def one_step():
            holder["state"], holder["rng"], losses, _ = multi(
                holder["state"], stacked, holder["rng"]
            )
            holder["loss"] = losses[-1]
    else:

        def one_step():
            holder["state"], holder["loss"] = step(holder["state"], x, y)

    for _ in range(2 if scan_k > 1 else (30 if on_tpu else 3)):
        one_step()
    _value_barrier(holder)
    log(f"jax cnn warmup done ({batch} samples/step, scan_k={scan_k})")

    barrier = lambda: _value_barrier(holder)  # noqa: E731
    # Window length targets ~TPU_CNN_STEPS *real* steps regardless of how
    # many are fused per dispatch.
    cnn_steps = max(TPU_CNN_STEPS // scan_k, 1) if on_tpu else CNN_STEPS
    times = _time_trials(one_step, CNN_TRIALS, cnn_steps, barrier)
    paired = {}
    head_steps, head_times = cnn_steps * scan_k, times
    if on_tpu and LONG_WINDOW > 1:
        steps_long = cnn_steps * LONG_WINDOW
        times_long = _time_trials(one_step, CNN_TRIALS, steps_long, barrier)
        paired = _paired_window_stats(
            times, times_long, cnn_steps * scan_k, steps_long * scan_k,
            batch / n_chips,
        )
        head_steps, head_times = steps_long * scan_k, times_long
    sps = sorted(batch * head_steps / dt / n_chips for dt in head_times)
    median = statistics.median(sps)
    flops_step = cnn_train_flops_per_step(batch)
    peak = _peak_flops(device)
    achieved = flops_step * head_steps / statistics.median(head_times) / n_chips
    mfu = _check_mfu(achieved, peak, "CNN")
    out = {
        "value": round(median, 1),
        "unit": "samples/sec/chip",
        "median": round(median, 1),
        "max": round(sps[-1], 1),
        "trials": [round(x, 1) for x in sps],
        "spread": round(sps[-1] / sps[0], 2) if sps[0] else None,
        "steps_per_trial": head_steps,
        "scan_k": scan_k,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "batch_per_chip": CNN_BATCH_PER_CHIP,
    }
    if paired:
        out["paired_window"] = paired
    return out


def bench_torch_transformer() -> float | None:
    """Reference-equivalent engine: torch.nn.Transformer, same shapes, CPU."""
    if os.environ.get("BENCH_SKIP_TORCH"):
        return None
    try:
        import torch
        import torch.nn as tnn

        torch.manual_seed(0)
        d, steps = D_MODEL, int(os.environ.get("BENCH_TORCH_STEPS", "10"))
        batch = min(BATCH_PER_CHIP, 32)

        class Ref(tnn.Module):
            def __init__(self):
                super().__init__()
                self.src_emb = tnn.Embedding(SRC_VOCAB, d)
                self.trg_emb = tnn.Embedding(TRG_VOCAB, d)
                self.core = tnn.Transformer(
                    d_model=d, nhead=HEADS, num_encoder_layers=LAYERS,
                    num_decoder_layers=LAYERS, dim_feedforward=FFN,
                    dropout=0.1, batch_first=True,
                )
                self.head = tnn.Linear(d, TRG_VOCAB)

            def forward(self, src, trg):
                mask = tnn.Transformer.generate_square_subsequent_mask(trg.shape[1])
                return self.head(
                    self.core(self.src_emb(src), self.trg_emb(trg), tgt_mask=mask)
                )

        model = Ref()
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        loss_fn = tnn.CrossEntropyLoss(ignore_index=0)
        src = torch.randint(1, SRC_VOCAB, (batch, SEQ))
        trg = torch.randint(1, TRG_VOCAB, (batch, SEQ))

        def one_step():
            opt.zero_grad()
            logits = model(src, trg[:, :-1])
            loss = loss_fn(logits.reshape(-1, TRG_VOCAB), trg[:, 1:].reshape(-1))
            loss.backward()
            opt.step()

        one_step()  # warmup
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        dt = time.perf_counter() - t0
        tps = batch * SEQ * steps / dt
        log(f"torch-cpu transformer baseline: {steps} steps in {dt:.3f}s → "
            f"{tps:,.0f} tokens/sec")
        return tps
    except Exception as e:  # baked-in torch should work; degrade gracefully
        log(f"torch transformer baseline unavailable: {e!r}")
        return None


def bench_torch_cnn() -> float | None:
    """Reference-equivalent CNN engine: FashionMNISTModel shapes, CPU."""
    if os.environ.get("BENCH_SKIP_TORCH"):
        return None
    try:
        import torch
        import torch.nn as tnn

        torch.manual_seed(0)
        steps = int(os.environ.get("BENCH_TORCH_STEPS", "10"))
        batch = min(CNN_BATCH_PER_CHIP, 512)
        h = 10

        model = tnn.Sequential(
            tnn.Conv2d(1, h, 3, padding=1), tnn.ReLU(),
            tnn.Conv2d(h, h, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
            tnn.Conv2d(h, h, 3, padding=1), tnn.ReLU(),
            tnn.Conv2d(h, h, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2),
            tnn.Flatten(), tnn.Linear(h * 7 * 7, 10),
        )
        opt = torch.optim.SGD(model.parameters(), lr=0.01)
        loss_fn = tnn.CrossEntropyLoss()
        x = torch.randn(batch, 1, 28, 28)
        y = torch.randint(0, 10, (batch,))

        def one_step():
            opt.zero_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()

        one_step()
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        dt = time.perf_counter() - t0
        sps = batch * steps / dt
        log(f"torch-cpu cnn baseline: {steps} steps in {dt:.3f}s → "
            f"{sps:,.0f} samples/sec")
        return sps
    except Exception as e:
        log(f"torch cnn baseline unavailable: {e!r}")
        return None


def main() -> None:
    result = {
        "metric": "transformer_mt_train_throughput",
        "value": 0.0,
        "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,
    }
    try:
        jax = _init_backend()
        _degraded_mode_knobs(jax)
    except Exception as e:
        log(traceback.format_exc())
        result["error"] = repr(e)
        print(json.dumps(result))
        return
    # Imported only after backend init (the package __init__ is heavy);
    # every stage below runs under a bench.<label> span. With
    # MLSPARK_TELEMETRY=0 these are shared no-op context managers — the
    # stage timings are unaffected (the <2% train-step criterion).
    from machine_learning_apache_spark_tpu import telemetry
    # The two workloads degrade independently: a transformer failure must
    # not suppress the CNN measurement, and vice versa. Exception: once any
    # deadline fires, its abandoned thread may STILL be running on the chip
    # whenever the RPC un-wedges — later stages would measure contention.
    # Policy: a TimeoutError quarantines the device; later device stages
    # are skipped (scanned/sweep) or flagged "after_timeout" (cnn, kept for
    # artifact completeness).
    deadline = float(os.environ.get("BENCH_WORKLOAD_DEADLINE", "900"))
    # Total-run ledger: on a live TPU the full 6-stage plan can run ~45-75
    # min; if the invoking harness kills the process first there is NO
    # artifact at all — strictly worse than a partial one. Optional stages
    # are skipped (recorded as such) once the budget is too thin, always
    # reserving room for the CNN stage (kept for artifact completeness).
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "2700"))
    t_start = time.monotonic()
    cnn_reserve = 420.0

    def _budget_left(reserve: float = cnn_reserve) -> float:
        return total_budget - (time.monotonic() - t_start) - reserve

    def _stage_deadline(label: str) -> float | None:
        """Deadline for the next OPTIONAL stage; None = ledger says skip."""
        left = _budget_left()
        if left < 120:
            log(f"{label} skipped: total budget exhausted "
                f"({left + cnn_reserve:.0f}s of {total_budget:.0f}s left)")
            return None
        return min(deadline, left)

    suspect = False

    def _run_stage(label: str, work) -> dict:
        """Budget-checked, retried, deadline-wrapped optional stage. The
        ledger is re-consulted on EVERY attempt — a transient-failure retry
        must not re-arm a deadline the budget can no longer cover."""
        nonlocal suspect

        def attempt():
            d = _stage_deadline(label)
            if d is None:
                raise _BudgetExhausted(label)
            return _with_deadline(work, d, label)

        try:
            with telemetry.span(f"bench.{label}"):
                return _transient_retry(attempt, label)
        except _BudgetExhausted:
            return {"skipped": "total budget"}
        except Exception as e:
            log(traceback.format_exc())
            suspect = suspect or isinstance(e, TimeoutError)
            return {"error": repr(e)}

    try:
        # The headline is never skipped (it IS the artifact) — a thin
        # ledger clamps its deadline instead, with a 300s floor so the
        # measurement can still land.
        head_d = max(min(deadline, _budget_left()), 300.0)
        with telemetry.span("bench.transformer"):
            mt = _transient_retry(
                lambda: _with_deadline(
                    lambda: bench_transformer(jax), head_d, "transformer"
                ),
                "transformer",
            )
        baseline = bench_torch_transformer()
        result["value"] = mt["median"]
        result["vs_baseline"] = round(mt["median"] / baseline, 3) if baseline else 1.0
        result.update(mt)
    except Exception as e:
        log(traceback.format_exc())
        result["error"] = repr(e)
        suspect = suspect or isinstance(e, TimeoutError)
    if _tpu_stages(jax) and not suspect and not os.environ.get(
        "BENCH_SKIP_SCANNED"
    ):
        # The same MT workload through the scanned product path
        # (fit(steps_per_call=K) semantics): K=8 steps per dispatch removes
        # the per-dispatch host cost the paired-window estimator can only
        # model. Reported alongside (not replacing) the per-step headline.
        sc = _run_stage(
            "transformer-scanned",
            lambda: bench_transformer(
                jax, scan_k=8, trials=5, steps=10, warmup=20
            ),
        )
        if "error" in sc or "skipped" in sc:
            result["scanned"] = sc
        else:
            result["scanned"] = {
                k: sc[k]
                for k in (
                    "median", "max", "trials", "spread",
                    "steps_per_trial", "scan_k", "mfu", "paired_window",
                )
                if k in sc
            }
    if _tpu_stages(jax) and not suspect and not os.environ.get(
        "BENCH_SKIP_PACKED"
    ):
        # Sequence packing on the same workload: pairs/sec/chip against the
        # fixed-width layout's (token rate)/SEQ ceiling.
        pk = _run_stage("packed", lambda: bench_packed_transformer(jax))
        if "pairs_per_sec_chip" in pk and result.get("median"):
            pk["vs_unpacked_pairs_rate"] = round(
                pk["pairs_per_sec_chip"] / (result["median"] / SEQ), 2
            )
        result["packed"] = pk
    if _tpu_stages(jax) and not suspect and not os.environ.get(
        "BENCH_SKIP_COMPOSED"
    ):
        # The three throughput levers composed (packing × scan × bs=512):
        # the "best achievable tokens/sec/chip" record a real user would
        # run at, alongside (never replacing) the reference-shape headline.
        result["composed"] = _run_stage(
            "composed",
            lambda: bench_composed(
                jax,
                batch_per_chip=int(
                    os.environ.get("BENCH_COMPOSED_BATCH", "512")
                ),
                scan_k=int(os.environ.get("BENCH_COMPOSED_SCAN", "4")),
            ),
        )
    if _tpu_stages(jax) and not suspect and not os.environ.get(
        "BENCH_SKIP_SWEEP"
    ):
        # Own try-block, gated on the platform (not the headline result):
        # neither a headline failure nor a sweep failure may void the other,
        # and a mid-sweep hang keeps the completed points. The sweep checks
        # the same deadline between points itself; the thread-abandon
        # wrapper is only the backstop for one wedged call.
        d = _stage_deadline("sweep")
        if d is None:
            # Same skip shape as the other stages (a deliberate skip is not
            # a failure); the evidence recorder excludes dict-shaped sweeps.
            result["sweep"] = {"skipped": "total budget"}
        else:
            sweep_points: list = []
            try:
                with telemetry.span("bench.sweep"):
                    result["sweep"] = _with_deadline(
                        lambda: bench_transformer_sweep(
                            jax, sweep_points, stop_at=time.monotonic() + d
                        ),
                        d + 60, "sweep",
                    )
            except Exception as e:
                log(traceback.format_exc())
                # Snapshot: the abandoned thread could still append
                # mid-dumps.
                result["sweep"] = list(sweep_points)
                result["sweep_error"] = repr(e)
                suspect = suspect or isinstance(e, TimeoutError)
    if not suspect:
        # A point that hung inside the sweep's own loop quarantines too
        # (the sweep returns normally after recording it) — unless the
        # point ran isolated, where the hang died with its own process and
        # the chip this process holds was never touched.
        suspect = any(
            "TimeoutError" in p.get("error", "")
            for p in (result.get("sweep") or [])
            if isinstance(p, dict) and not p.get("isolated")
        )
    try:
        # CNN runs on whatever the ledger has left (its reserve), capped by
        # the per-workload deadline — never skipped outright, floored so
        # the measurement can still land.
        cnn_d = max(min(deadline, _budget_left(reserve=0.0)), 120.0)
        with telemetry.span("bench.cnn"):
            cnn = _transient_retry(
                lambda: _with_deadline(lambda: bench_cnn(jax), cnn_d, "cnn"),
                "cnn",
            )
        cnn_base = bench_torch_cnn()
        cnn["vs_baseline"] = (
            round(cnn["value"] / cnn_base, 3) if cnn_base else 1.0
        )
        if suspect:
            # Kept for artifact completeness, but an earlier abandoned
            # thread may contend on the chip — do not cite this number.
            cnn["after_timeout"] = True
        result["cnn"] = cnn
    except Exception as e:
        log(traceback.format_exc())
        result["cnn"] = {"error": repr(e)}
    # The evidence contract (VERDICT r04 item 2): a TPU number in the
    # artifact whichever way the tunnel rolls. On-chip runs refresh the
    # committed record; CPU fallbacks embed it, labeled with capture date.
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        on_tpu = False
    if on_tpu and not suspect:
        _record_tpu_evidence(result)
    elif not on_tpu:
        ev = _load_tpu_evidence()
        if ev:
            result["tpu_evidence"] = ev
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--sweep-point":
        sys.exit(_sweep_point_main(sys.argv[2]))
    main()
