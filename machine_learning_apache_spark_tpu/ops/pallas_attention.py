"""Blockwise flash attention as a Pallas TPU kernel.

The reference's attention core materializes the full ``[S, S]`` score matrix
(``transformer.py:12-25``). On TPU that is HBM-bandwidth-bound and caps the
sequence length; this kernel streams K/V blocks through VMEM with an online
softmax (running max / denominator / output accumulator in scratch), never
materializing scores — the flash-attention recurrence:

    m_new = max(m, rowmax(S_blk))
    l_new = l * exp(m - m_new) + rowsum(exp(S_blk - m_new))
    acc   = acc * exp(m - m_new) + exp(S_blk - m_new) @ V_blk

Grid = (batch*heads, q_blocks, k_blocks) with the k axis innermost and
sequential, so the scratch accumulators persist across k iterations of one
q block. The same per-block accumulator is what ``parallel/ring_attention.py``
rotates over ICI for sequence parallelism (SURVEY.md §5 long-context seam).

Numerics are float32 in the accumulators regardless of input dtype
(bfloat16-friendly: matmuls feed the MXU in the input dtype, reductions stay
exact enough to train).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    *refs,
    has_kv_valid: bool,
    causal: bool,
    causal_offset: int,
    kv_len: int,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
    scale: float,
):
    # The kv_valid operand exists only when a mask was passed — the unmasked
    # hot path pays no extra HBM traffic or per-tile AND.
    if has_kv_valid:
        kv_valid_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        kv_valid_ref = None
        o_ref, m_scr, l_scr, acc_scr = refs
    i = pl.program_id(1)  # query-block index
    j = pl.program_id(2)  # key-block index (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Under causality, key blocks strictly above the diagonal contribute
    # nothing — skip their compute entirely (this is where flash attention
    # halves the FLOPs).
    needed = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal
        else True
    )

    @pl.when(needed)
    def _block():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < kv_len  # wrapper zero-pads K; padded keys masked here
        if has_kv_valid:
            # Per-key validity (padding mask): [1, block_k] over rows.
            mask = mask & (kv_valid_ref[0] != 0)
        if causal:
            # Bottom-right-aligned diagonal: the last real query row sees all
            # kv_len keys even when q_len != kv_len (decode convention).
            q_idx = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (k_idx <= q_idx + causal_offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero for masked entries: when a row's running max is still
        # NEG_INF (no valid key seen yet), exp(s - m) would be exp(0)=1 and
        # silently average V; zeroing keeps l=0 so _finalize emits zeros.
        p = jnp.where(mask, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_cur

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (query padding) have l == 0; emit zeros, not NaN.
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    *,
    causal: bool = False,
    kv_valid: jnp.ndarray | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention over ``[B, H, S, d]`` streams.

    Query/key lengths may differ (fixing reference quirk Q8). Head dim is
    zero-padded to the 128-lane boundary; sequence dims to the block size —
    padding is masked inside the kernel and sliced off the output.

    ``kv_valid`` (``[B, S_k]`` bool) masks invalid keys per batch row — the
    padding-mask case of the MT model (``make_padding_mask`` semantics),
    streamed through the kernel instead of materializing ``[B, Sq, Sk]``.

    Differentiable: the forward pass streams through the kernel; the
    backward recomputes attention on the fused-XLA path (a dedicated Pallas
    backward kernel is the documented follow-up — for long-context
    *training* memory the sequence-sharded ``parallel.ring_attention`` is
    the intended path).
    """
    cfg = (causal, block_q, block_k, interpret)
    if kv_valid is None:
        return _flash_vjp_nomask(cfg, query, key, value)
    return _flash_vjp_masked(cfg, query, key, value, kv_valid)


def _dense_reference(query, key, value, causal, kv_valid):
    from machine_learning_apache_spark_tpu.ops.attention import (
        dot_product_attention,
    )

    # One source of truth for structured→dense mask semantics.
    return dot_product_attention(
        query, key, value, causal=causal, kv_valid=kv_valid, use_pallas=False
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_vjp_nomask(cfg, query, key, value):
    return _flash_forward(query, key, value, None, *cfg)


def _flash_nomask_fwd(cfg, query, key, value):
    return _flash_vjp_nomask(cfg, query, key, value), (query, key, value)


def _flash_nomask_bwd(cfg, res, g):
    query, key, value = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, cfg[0], None),
        query, key, value,
    )
    return vjp(g)


_flash_vjp_nomask.defvjp(_flash_nomask_fwd, _flash_nomask_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_vjp_masked(cfg, query, key, value, kv_valid):
    return _flash_forward(query, key, value, kv_valid, *cfg)


def _flash_masked_fwd(cfg, query, key, value, kv_valid):
    out = _flash_vjp_masked(cfg, query, key, value, kv_valid)
    return out, (query, key, value, kv_valid)


def _flash_masked_bwd(cfg, res, g):
    query, key, value, kv_valid = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, cfg[0], kv_valid),
        query, key, value,
    )
    return (*vjp(g), None)


_flash_vjp_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def _flash_forward(
    query, key, value, kv_valid, causal, block_q, block_k, interpret
):
    b, h, q_len, d = query.shape
    kv_len = key.shape[2]
    scale = 1.0 / math.sqrt(d)

    block_q = min(block_q, max(8, -(-q_len // 8) * 8))
    block_k = min(block_k, max(128, -(-kv_len // 128) * 128))

    q = _pad_to(_pad_to(query, 2, block_q), 3, 128)
    k = _pad_to(_pad_to(key, 2, block_k), 3, 128)
    v = _pad_to(_pad_to(value, 2, block_k), 3, 128)
    d_pad = q.shape[3]
    q_pad, k_pad = q.shape[2], k.shape[2]

    bh = b * h
    q = q.reshape(bh, q_pad, d_pad)
    k = k.reshape(bh, k_pad, d_pad)
    v = v.reshape(bh, k_pad, d_pad)
    num_q_blocks = q_pad // block_q
    num_k_blocks = k_pad // block_k

    operands = [q, k, v]
    valid_specs = []
    if kv_valid is not None:
        if kv_valid.shape != (b, kv_len):
            raise ValueError(
                f"kv_valid must be [batch={b}, kv_len={kv_len}], "
                f"got {kv_valid.shape}"
            )
        # [B, 1, k_pad]: a singleton middle dim keeps the TPU block tiling
        # legal (block dim -2 == array dim -2); batch row = grid0 // heads.
        operands.append(
            _pad_to(kv_valid.astype(jnp.int32), 1, block_k)[:, None, :]
        )
        valid_specs.append(
            pl.BlockSpec(
                (1, 1, block_k), lambda bh_i, i, j, h=h: (bh_i // h, 0, j)
            )
        )

    kernel = functools.partial(
        _flash_kernel,
        has_kv_valid=kv_valid is not None,
        causal=causal,
        causal_offset=kv_len - q_len,
        kv_len=kv_len,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
        scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            *valid_specs,
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_pad, d_pad), query.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)

    return out.reshape(b, h, q_pad, d_pad)[:, :, :q_len, :d]
