"""Blockwise flash attention as a Pallas TPU kernel.

The reference's attention core materializes the full ``[S, S]`` score matrix
(``transformer.py:12-25``). On TPU that is HBM-bandwidth-bound and caps the
sequence length; this kernel streams K/V blocks through VMEM with an online
softmax (running max / denominator / output accumulator in scratch), never
materializing scores — the flash-attention recurrence:

    m_new = max(m, rowmax(S_blk))
    l_new = l * exp(m - m_new) + rowsum(exp(S_blk - m_new))
    acc   = acc * exp(m - m_new) + exp(S_blk - m_new) @ V_blk

Grid = (batch*heads, q_blocks, k_blocks) with the k axis innermost and
sequential, so the scratch accumulators persist across k iterations of one
q block. The same per-block accumulator is what ``parallel/ring_attention.py``
rotates over ICI for sequence parallelism (SURVEY.md §5 long-context seam).

Numerics are float32 in the accumulators regardless of input dtype
(bfloat16-friendly: matmuls feed the MXU in the input dtype, reductions stay
exact enough to train).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from machine_learning_apache_spark_tpu.utils.jax_compat import (
    pallas_tpu_compiler_params,
)

NEG_INF = -1e30


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    *refs,
    has_kv_valid: bool,
    return_lse: bool,
    causal: bool,
    causal_offset: int,
    kv_len: int,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
    scale: float,
):
    # The kv_valid operand exists only when a mask was passed — the unmasked
    # hot path pays no extra HBM traffic or per-tile AND. The lse output
    # exists only under differentiation (the backward kernels recompute
    # probabilities from it instead of saving the [S, S] matrix).
    refs = list(refs)
    kv_valid_ref = refs.pop(0) if has_kv_valid else None
    o_ref = refs.pop(0)
    lse_ref = refs.pop(0) if return_lse else None
    m_scr, l_scr, acc_scr = refs
    i = pl.program_id(1)  # query-block index
    j = pl.program_id(2)  # key-block index (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Under causality, key blocks strictly above the diagonal contribute
    # nothing — skip their compute entirely (this is where flash attention
    # halves the FLOPs).
    needed = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal
        else True
    )

    @pl.when(needed)
    def _block():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < kv_len  # wrapper zero-pads K; padded keys masked here
        if has_kv_valid:
            # Per-key validity (padding mask): [1, block_k] over rows.
            mask = mask & (kv_valid_ref[0] != 0)
        if causal:
            # Bottom-right-aligned diagonal: the last real query row sees all
            # kv_len keys even when q_len != kv_len (decode convention).
            q_idx = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (k_idx <= q_idx + causal_offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero for masked entries: when a row's running max is still
        # NEG_INF (no valid key seen yet), exp(s - m) would be exp(0)=1 and
        # silently average V; zeroing keeps l=0 so _finalize emits zeros.
        p = jnp.where(mask, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_cur

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (query padding) have l == 0; emit zeros, not NaN.
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        if return_lse:
            # Row softmax normalizer in log space; NEG_INF marks fully-masked
            # rows so the backward masks them out entirely.
            lse_ref[0] = jnp.where(
                l == 0.0, NEG_INF, m_scr[:] + jnp.log(safe_l)
            )[:, 0]


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    *,
    causal: bool = False,
    kv_valid: jnp.ndarray | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention over ``[B, H, S, d]`` streams.

    Query/key lengths may differ (fixing reference quirk Q8). Head dim is
    zero-padded to the 128-lane boundary; sequence dims to the block size —
    padding is masked inside the kernel and sliced off the output.

    ``kv_valid`` (``[B, S_k]`` bool) masks invalid keys per batch row — the
    padding-mask case of the MT model (``make_padding_mask`` semantics),
    streamed through the kernel instead of materializing ``[B, Sq, Sk]``.

    Differentiable end to end: the forward streams through the kernel and
    saves per-row log-sum-exp statistics; the backward recomputes block
    probabilities from them in two more Pallas launches (flash-2 style dq
    and dk/dv kernels) — O(S) memory in both directions, which is what makes
    long-context *training* affordable. Below ``PALLAS_BWD_MIN_SCORES``
    score elements the backward falls back to the fused-XLA dense recompute
    (cheaper than two kernel launches at short sequence lengths).
    """
    cfg = (causal, block_q, block_k, interpret)
    if kv_valid is None:
        return _flash_vjp_nomask(cfg, query, key, value)
    return _flash_vjp_masked(cfg, query, key, value, kv_valid)


def _dense_reference(query, key, value, causal, kv_valid):
    from machine_learning_apache_spark_tpu.ops.attention import (
        dot_product_attention,
    )

    # One source of truth for structured→dense mask semantics.
    return dot_product_attention(
        query, key, value, causal=causal, kv_valid=kv_valid, use_pallas=False
    )


# Below this many score-matrix elements the fused-XLA dense recompute is
# both affordable and faster than a second kernel launch pair; above it the
# blockwise backward avoids materializing [S_q, S_k] chains entirely (the
# long-context training seam).
PALLAS_BWD_MIN_SCORES = 256 * 1024


def _use_pallas_bwd(q_len: int, kv_len: int) -> bool:
    return q_len * kv_len >= PALLAS_BWD_MIN_SCORES


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_vjp_nomask(cfg, query, key, value):
    return _flash_forward(query, key, value, None, *cfg)


def _flash_nomask_fwd(cfg, query, key, value):
    # The out/lse residuals are only kept when the pallas backward will read
    # them (shape-static decision); the short-sequence dense fallback keeps
    # the lean (q, k, v) residuals and skips the lse output entirely.
    if _use_pallas_bwd(query.shape[2], key.shape[2]):
        out, lse = _flash_forward(
            query, key, value, None, *cfg, return_lse=True
        )
        return out, (query, key, value, out, lse)
    return _flash_vjp_nomask(cfg, query, key, value), (query, key, value, None, None)


def _flash_nomask_bwd(cfg, res, g):
    query, key, value, out, lse = res
    if _use_pallas_bwd(query.shape[2], key.shape[2]):
        return _flash_backward(cfg, query, key, value, None, out, lse, g)
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, cfg[0], None),
        query, key, value,
    )
    return vjp(g)


_flash_vjp_nomask.defvjp(_flash_nomask_fwd, _flash_nomask_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_vjp_masked(cfg, query, key, value, kv_valid):
    return _flash_forward(query, key, value, kv_valid, *cfg)


def _flash_masked_fwd(cfg, query, key, value, kv_valid):
    if _use_pallas_bwd(query.shape[2], key.shape[2]):
        out, lse = _flash_forward(
            query, key, value, kv_valid, *cfg, return_lse=True
        )
        return out, (query, key, value, kv_valid, out, lse)
    return (
        _flash_vjp_masked(cfg, query, key, value, kv_valid),
        (query, key, value, kv_valid, None, None),
    )


def _flash_masked_bwd(cfg, res, g):
    query, key, value, kv_valid, out, lse = res
    if _use_pallas_bwd(query.shape[2], key.shape[2]):
        return (
            *_flash_backward(cfg, query, key, value, kv_valid, out, lse, g),
            None,
        )
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, cfg[0], kv_valid),
        query, key, value,
    )
    return (*vjp(g), None)


_flash_vjp_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
    has_kv_valid: bool, causal: bool, causal_offset: int,
    q_len: int, kv_len: int, block_q: int, block_k: int,
    num_k_blocks: int, scale: float,
):
    """dQ = Σ_j dS_ij @ K_j, streaming K/V blocks (flash-2 backward, q side).

    Probabilities are recomputed per block from the saved row normalizer
    (``lse``) — no [S_q, S_k] tensor is ever read or written.
    """
    if has_kv_valid:
        kv_valid_ref, dq_ref, dq_scr = refs
    else:
        kv_valid_ref = None
        dq_ref, dq_scr = refs
    i = pl.program_id(1)  # query block
    j = pl.program_id(2)  # key block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal
        else True
    )

    @pl.when(needed)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]      # [block_q, 1]
        delta = delta_ref[0]  # [block_q, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        q_idx = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = (k_idx < kv_len) & (q_idx < q_len)
        if has_kv_valid:
            mask = mask & (kv_valid_ref[0] != 0)
        if causal:
            mask = mask & (k_idx <= q_idx + causal_offset)
        # Fully-masked rows carry lse == NEG_INF; exp would overflow to inf
        # before the where, so gate on a finite normalizer too.
        mask = mask & (lse > NEG_INF * 0.5)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
    has_kv_valid: bool, causal: bool, causal_offset: int,
    q_len: int, kv_len: int, block_q: int, block_k: int,
    num_q_blocks: int, scale: float,
):
    """dK_j = Σ_i dSᵀ_ij @ Q_i, dV_j = Σ_i Pᵀ_ij @ dO_i — the k/v side,
    streaming Q/dO blocks with scores computed transposed ([block_k,
    block_q]) so both accumulators live in k-block scratch."""
    if has_kv_valid:
        kv_valid_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        kv_valid_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    j = pl.program_id(1)  # key block
    i = pl.program_id(2)  # query block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal
        else True
    )

    @pl.when(needed)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]      # [1, block_q] (row layout over q columns)
        delta = delta_ref[0]  # [1, block_q]
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0
        )
        q_idx = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1
        )
        mask = (k_idx < kv_len) & (q_idx < q_len)
        if has_kv_valid:
            mask = mask & (kv_valid_ref[0] != 0)  # [block_k, 1] column layout
        if causal:
            mask = mask & (k_idx <= q_idx + causal_offset)
        mask = mask & (lse > NEG_INF * 0.5)
        p_t = jnp.where(mask, jnp.exp(s_t - lse), 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds_t = p_t * (dp_t - delta)
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(cfg, query, key, value, kv_valid, out, lse, g):
    """Blockwise dq/dk/dv (flash-2): two kernel launches, O(S) memory.

    ``lse`` arrives [B*H, q_pad] from the forward (same block clamping, so
    the padded length matches); ``delta = rowsum(dO ∘ O)`` is a cheap fused
    XLA reduction computed here, not a kernel.
    """
    causal, block_q, block_k, interpret = cfg
    b, h, q_len, d = query.shape
    kv_len = key.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q, block_k = _block_sizes(q_len, kv_len, block_q, block_k)

    q = _pad_to(_pad_to(query, 2, block_q), 3, 128)
    k = _pad_to(_pad_to(key, 2, block_k), 3, 128)
    v = _pad_to(_pad_to(value, 2, block_k), 3, 128)
    do = _pad_to(_pad_to(g, 2, block_q), 3, 128).astype(query.dtype)
    d_pad = q.shape[3]
    q_pad, k_pad = q.shape[2], k.shape[2]
    bh = b * h
    q = q.reshape(bh, q_pad, d_pad)
    k = k.reshape(bh, k_pad, d_pad)
    v = v.reshape(bh, k_pad, d_pad)
    do = do.reshape(bh, q_pad, d_pad)
    num_q_blocks = q_pad // block_q
    num_k_blocks = k_pad // block_k

    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(bh, q_len)
    delta = _pad_to(delta, 1, block_q)

    # Column ([.., q_pad, 1]) and row ([.., 1, q_pad]) layouts of the per-row
    # statistics: the dq kernel broadcasts them down k columns, the dkv
    # kernel across q columns — Mosaic-friendly 2D blocks either way.
    lse_col, delta_col = lse[:, :, None], delta[:, :, None]
    lse_row, delta_row = lse[:, None, :], delta[:, None, :]

    common = dict(
        causal=causal,
        causal_offset=kv_len - q_len,
        q_len=q_len,
        kv_len=kv_len,
        block_q=block_q,
        block_k=block_k,
        scale=scale,
        has_kv_valid=kv_valid is not None,
    )
    qkvdo_specs = [
        pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
    ]
    compiler_params = pallas_tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )

    dq_operands = [q, k, v, do, lse_col, delta_col]
    dq_specs = [
        *qkvdo_specs,
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    if kv_valid is not None:
        valid = _pad_to(kv_valid.astype(jnp.int32), 1, block_k)
        dq_operands.append(valid[:, None, :])
        dq_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j, h=h: (b // h, 0, j))
        )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, num_k_blocks=num_k_blocks, **common
        ),
        grid=(bh, num_q_blocks, num_k_blocks),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_pad, d_pad), query.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        compiler_params=compiler_params,
        interpret=interpret,
    )(*dq_operands)

    # dkv grid: key blocks in the middle (parallel), query blocks innermost
    # (sequential) so the dk/dv accumulators persist across the q sweep.
    dkv_operands = [q, k, v, do, lse_row, delta_row]
    dkv_specs = [
        pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
    ]
    if kv_valid is not None:
        dkv_operands.append(valid[:, :, None])
        dkv_specs.append(
            pl.BlockSpec((1, block_k, 1), lambda b, j, i, h=h: (b // h, j, 0))
        )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, num_q_blocks=num_q_blocks, **common
        ),
        grid=(bh, num_k_blocks, num_q_blocks),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, k_pad, d_pad), key.dtype),
            jax.ShapeDtypeStruct((bh, k_pad, d_pad), value.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(*dkv_operands)

    dq = dq.reshape(b, h, q_pad, d_pad)[:, :, :q_len, :d]
    dk = dk.reshape(b, h, k_pad, d_pad)[:, :, :kv_len, :d]
    dv = dv.reshape(b, h, k_pad, d_pad)[:, :, :kv_len, :d]
    return dq, dk, dv


def _block_sizes(q_len: int, kv_len: int, block_q: int, block_k: int):
    return (
        min(block_q, max(8, -(-q_len // 8) * 8)),
        min(block_k, max(128, -(-kv_len // 128) * 128)),
    )


def _flash_forward(
    query, key, value, kv_valid, causal, block_q, block_k, interpret,
    return_lse: bool = False,
):
    b, h, q_len, d = query.shape
    kv_len = key.shape[2]
    scale = 1.0 / math.sqrt(d)

    block_q, block_k = _block_sizes(q_len, kv_len, block_q, block_k)

    q = _pad_to(_pad_to(query, 2, block_q), 3, 128)
    k = _pad_to(_pad_to(key, 2, block_k), 3, 128)
    v = _pad_to(_pad_to(value, 2, block_k), 3, 128)
    d_pad = q.shape[3]
    q_pad, k_pad = q.shape[2], k.shape[2]

    bh = b * h
    q = q.reshape(bh, q_pad, d_pad)
    k = k.reshape(bh, k_pad, d_pad)
    v = v.reshape(bh, k_pad, d_pad)
    num_q_blocks = q_pad // block_q
    num_k_blocks = k_pad // block_k

    operands = [q, k, v]
    valid_specs = []
    if kv_valid is not None:
        if kv_valid.shape != (b, kv_len):
            raise ValueError(
                f"kv_valid must be [batch={b}, kv_len={kv_len}], "
                f"got {kv_valid.shape}"
            )
        # [B, 1, k_pad]: a singleton middle dim keeps the TPU block tiling
        # legal (block dim -2 == array dim -2); batch row = grid0 // heads.
        operands.append(
            _pad_to(kv_valid.astype(jnp.int32), 1, block_k)[:, None, :]
        )
        valid_specs.append(
            pl.BlockSpec(
                (1, 1, block_k), lambda bh_i, i, j, h=h: (bh_i // h, 0, j)
            )
        )

    kernel = functools.partial(
        _flash_kernel,
        has_kv_valid=kv_valid is not None,
        return_lse=return_lse,
        causal=causal,
        causal_offset=kv_len - q_len,
        kv_len=kv_len,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
        scale=scale,
    )
    out_specs = [pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((bh, q_pad, d_pad), query.dtype)]
    if return_lse:
        out_specs.append(pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)))
        out_shape.append(jax.ShapeDtypeStruct((bh, q_pad), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            *valid_specs,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d_pad), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)

    out = res[0].reshape(b, h, q_pad, d_pad)[:, :, :q_len, :d]
    if return_lse:
        return out, res[1]  # lse stays [B*H, q_pad] for the backward kernels
    return out


# -- ragged paged-attention decode kernel ------------------------------------
#
# One query vector per request row, K/V gathered page-by-page through a
# block table (Ragged Paged Attention, arxiv 2604.15464). The block table
# and per-request lengths ride in as *scalar-prefetch* operands: the
# index_map of the K/V page operands reads `tbl[r, p]`, so the page DMA is
# data-dependent — the grid walks (request, page) but the pages fetched are
# whatever the allocator handed that request, in order. Pages past a
# request's length (block-table zero padding → the null page) are skipped
# by `pl.when` and their lanes masked, so arbitrary raggedness — including
# fully-inactive rows with length 0 — runs in the one compiled program.


def _ragged_paged_kernel(
    tbl_ref,  # scalar prefetch: [R, P] int32 block table
    len_ref,  # scalar prefetch: [R] int32 cached lengths
    q_ref,
    k_ref,
    v_ref,
    *refs,
    has_scale: bool,
    has_cur: bool,
    num_heads: int,
    heads_padded: int,
    head_dim: int,
    page_size: int,
    num_page_steps: int,
    scale: float,
):
    refs = list(refs)
    ks_ref = refs.pop(0) if has_scale else None
    vs_ref = refs.pop(0) if has_scale else None
    cur_k_ref = refs.pop(0) if has_cur else None
    cur_v_ref = refs.pop(0) if has_cur else None
    o_ref = refs.pop(0)
    m_scr, l_scr, acc_scr = refs
    r = pl.program_id(0)  # request row
    p = pl.program_id(1)  # page step (innermost, sequential)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[r]

    def _scores(keys, width):
        # Per-head block-diagonal q·kᵀ: the page store keeps heads packed
        # in the lane dim ([page, H*dh]), so each head is a static lane
        # slice — no in-kernel reshape/transpose of the DMA'd page.
        rows = [
            jax.lax.dot_general(
                q_ref[0][h : h + 1, :],
                keys[:, h * head_dim : (h + 1) * head_dim],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(num_heads)
        ]
        if heads_padded > num_heads:
            rows.append(
                jnp.full(
                    (heads_padded - num_heads, width), NEG_INF, jnp.float32
                )
            )
        return jnp.concatenate(rows, axis=0) * scale  # [Hs, width]

    def _weighted_values(probs, values, width):
        rows = [
            jax.lax.dot_general(
                probs[h : h + 1, :],
                values[:, h * head_dim : (h + 1) * head_dim],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(num_heads)
        ]
        if heads_padded > num_heads:
            rows.append(
                jnp.zeros((heads_padded - num_heads, head_dim), jnp.float32)
            )
        return jnp.concatenate(rows, axis=0)  # [Hs, dh]

    def _fold(s, mask, values, width):
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero where masked: a row whose running max is still
        # NEG_INF would otherwise see exp(0)=1 and silently average V.
        pr = jnp.where(mask, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(pr, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + _weighted_values(pr, values, width)
        m_scr[:] = m_cur

    @pl.when(p * page_size < length)
    def _page():
        k_idx = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        keys, values = k_ref[0], v_ref[0]
        if has_scale:
            # Dequantize the page *before* the dots — same order as the
            # XLA fallback, so kernel and fallback agree to float
            # rounding. Scales are per page-slot, broadcast over lanes.
            keys = keys.astype(jnp.float32) * ks_ref[0][:, None]
            values = values.astype(jnp.float32) * vs_ref[0][:, None]
        _fold(
            _scores(keys, page_size),
            k_idx < length,
            values,
            page_size,
        )

    @pl.when(p == num_page_steps - 1)
    def _finalize():
        if has_cur:
            # The current step's K/V — the causal diagonal — always valid,
            # folded once after the cached pages. Padded head rows carry
            # s == NEG_INF == m, so their weight exp(0) lands on zero
            # values and the l=1 denominator still emits zeros.
            _fold(
                _scores(cur_k_ref[0], 1),
                jnp.ones((1, 1), dtype=bool),
                cur_v_ref[0],
                1,
            )
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention_kernel(
    query: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    cur_k: jnp.ndarray | None = None,
    cur_v: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas form of ``ops.attention.ragged_paged_attention`` (see there
    for the contract). Grid ``(requests, page_steps)`` with the page axis
    sequential so the online-softmax scratch survives a request's sweep;
    K/V operands are one page per step, addressed through the
    scalar-prefetched block table — and so are the optional per-slot
    dequantization scales, which ride the *same* ``tbl[r, p]`` index map
    as their pages. On TPU this wants ``dh % 128 == 0`` and
    ``page_size % 8 == 0`` (``% 32`` for int8 pages — the dispatcher's
    gate); interpret mode (CPU tests) takes any shape."""
    num_rows, num_heads, head_dim = query.shape
    page_size, d_model = k_pages.shape[1], k_pages.shape[2]
    pages_per_req = block_table.shape[1]
    heads_padded = max(8, num_heads)
    if heads_padded > num_heads:
        query = jnp.pad(
            query, ((0, 0), (0, heads_padded - num_heads), (0, 0))
        )

    in_specs = [
        pl.BlockSpec(
            (1, heads_padded, head_dim), lambda r, p, tbl, lens: (r, 0, 0)
        ),
        pl.BlockSpec(
            (1, page_size, d_model),
            lambda r, p, tbl, lens: (tbl[r, p], 0, 0),
        ),
        pl.BlockSpec(
            (1, page_size, d_model),
            lambda r, p, tbl, lens: (tbl[r, p], 0, 0),
        ),
    ]
    operands = [
        block_table.astype(jnp.int32),
        lengths.astype(jnp.int32),
        query,
        k_pages,
        v_pages,
    ]
    if k_scale is not None:
        operands += [
            k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)
        ]
        in_specs += [
            pl.BlockSpec(
                (1, page_size), lambda r, p, tbl, lens: (tbl[r, p], 0)
            ),
            pl.BlockSpec(
                (1, page_size), lambda r, p, tbl, lens: (tbl[r, p], 0)
            ),
        ]
    if cur_k is not None:
        operands += [cur_k[:, None, :], cur_v[:, None, :]]
        in_specs += [
            pl.BlockSpec((1, 1, d_model), lambda r, p, tbl, lens: (r, 0, 0)),
            pl.BlockSpec((1, 1, d_model), lambda r, p, tbl, lens: (r, 0, 0)),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_rows, pages_per_req),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, heads_padded, head_dim), lambda r, p, tbl, lens: (r, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((heads_padded, 1), jnp.float32),
            pltpu.VMEM((heads_padded, 1), jnp.float32),
            pltpu.VMEM((heads_padded, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _ragged_paged_kernel,
            has_scale=k_scale is not None,
            has_cur=cur_k is not None,
            num_heads=num_heads,
            heads_padded=heads_padded,
            head_dim=head_dim,
            page_size=page_size,
            num_page_steps=pages_per_req,
            scale=1.0 / math.sqrt(head_dim),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_rows, heads_padded, head_dim), query.dtype
        ),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out[:, :num_heads]
