"""Sinusoidal positional encoding.

The reference recomputes the full PE table on **every forward call** and
device-transfers it each time (``transformer.py:33-42``, ``:60`` — quirk noted
at SURVEY.md C15). Here the table is computed once per (length, dim) at trace
time and baked into the compiled program as a constant — zero per-step cost
under jit.
"""

from __future__ import annotations

import functools

import numpy as np
import jax.numpy as jnp


@functools.lru_cache(maxsize=32)
def _table(length: int, dim: int) -> np.ndarray:
    # Same formula as transformer.py:33-42: even channels sin, odd cos, with
    # the 10000^(2i/d) frequency schedule.
    position = np.arange(length, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, dim, 2, dtype=np.float32) * (-np.log(10000.0) / dim))
    table = np.zeros((length, dim), dtype=np.float32)
    table[:, 0::2] = np.sin(position * div)
    table[:, 1::2] = np.cos(position * div[: dim // 2])
    return table


def sinusoidal_encoding(length: int, dim: int, dtype=jnp.float32) -> jnp.ndarray:
    """``[length, dim]`` sinusoidal table (``PositionalEncoding``,
    ``transformer.py:27-42``), cached host-side and constant-folded by XLA."""
    return jnp.asarray(_table(length, dim), dtype=dtype)
