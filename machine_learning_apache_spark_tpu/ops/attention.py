"""Scaled dot-product attention — the framework's hot kernel.

The reference's innermost compute (``scaled_dot_product``,
``transformer.py:12-25``) is QKᵀ/√d → mask → softmax → ·V. Correct-semantics
build (SURVEY.md quirk Q9 fixed): boolean mask (True = attendable) applied as
``where(mask, scores, -inf)`` *before* softmax, no permutes, and query/key
lengths are independent (Q8 fixed).

Two implementations behind one signature:

- ``scaled_dot_product_attention`` — pure ``jnp``; XLA fuses the softmax chain
  and tiles the matmuls onto the MXU. Works on every backend.
- ``machine_learning_apache_spark_tpu.ops.pallas_attention.flash_attention`` —
  blockwise online-softmax Pallas kernel for TPU (never materializes the
  [S, S] score matrix). ``dot_product_attention(..., use_pallas=True)``
  dispatches to it on TPU.

The blockwise structure is the design seam for ring/sequence-parallel
attention (SURVEY.md §5 long-context): the same per-block accumulator runs
under ``shard_map`` with K/V blocks rotating over ICI
(``parallel/ring_attention.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite -inf stand-in: keeps fully-masked rows NaN-free


def multi_head_attention_weights(
    query: jnp.ndarray,
    key: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """``softmax(QKᵀ/√d)`` with boolean masking — the first half of
    ``scaled_dot_product`` (``transformer.py:17-24``), returned separately
    because the reference also returns the attention map."""
    d_k = query.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", query, key)
    scores = scores / jnp.sqrt(jnp.asarray(d_k, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    # Softmax in float32 regardless of compute dtype: bfloat16 exp/renorm
    # loses enough precision to hurt training at long sequence lengths.
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return weights.astype(query.dtype)


def scaled_dot_product_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    *,
    return_weights: bool = False,
):
    """Attention over ``[..., S, d]`` streams (typically ``[B, H, S, d]``).

    ``mask`` is boolean, True = attendable, broadcastable to
    ``[..., Sq, Sk]``. Query and key sequence lengths may differ (the
    cross-attention case the reference mis-handles, Q8).
    """
    weights = multi_head_attention_weights(query, key, mask)
    values = jnp.einsum("...qk,...kd->...qd", weights, value)
    if return_weights:
        return values, weights
    return values


def dot_product_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    *,
    causal: bool = False,
    kv_valid: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Backend-dispatching attention entry point used by the model zoo.

    Masking comes in two forms:

    - dense ``mask`` (boolean, broadcastable to ``[..., Sq, Sk]``) — always
      takes the fused-XLA path (an arbitrary mask cannot stream through the
      blockwise kernel);
    - structured ``causal`` + ``kv_valid`` (``[B, S_k]`` per-key validity,
      the padding-mask case) — exactly the masks the zoo Transformer needs,
      streamed through the Pallas flash kernel on TPU without ever
      materializing ``[B, Sq, Sk]``.

    ``use_pallas=None`` auto-selects the flash kernel on TPU whenever the
    mask is structured-only.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and mask is None
    if use_pallas and mask is None:
        from machine_learning_apache_spark_tpu.ops.pallas_attention import (
            flash_attention,
        )

        return flash_attention(
            query, key, value, causal=causal, kv_valid=kv_valid
        )
    from machine_learning_apache_spark_tpu.ops.masks import (
        combine_masks,
        make_causal_mask,
    )

    if kv_valid is not None:
        mask = combine_masks(mask, kv_valid[:, None, None, :])
    if causal:
        mask = combine_masks(
            mask, make_causal_mask(query.shape[-2], key.shape[-2])
        )
    return scaled_dot_product_attention(query, key, value, mask)
