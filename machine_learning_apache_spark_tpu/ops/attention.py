"""Scaled dot-product attention — the framework's hot kernel.

The reference's innermost compute (``scaled_dot_product``,
``transformer.py:12-25``) is QKᵀ/√d → mask → softmax → ·V. Correct-semantics
build (SURVEY.md quirk Q9 fixed): boolean mask (True = attendable) applied as
``where(mask, scores, -inf)`` *before* softmax, no permutes, and query/key
lengths are independent (Q8 fixed).

Two implementations behind one signature:

- ``scaled_dot_product_attention`` — pure ``jnp``; XLA fuses the softmax chain
  and tiles the matmuls onto the MXU. Works on every backend.
- ``machine_learning_apache_spark_tpu.ops.pallas_attention.flash_attention`` —
  blockwise online-softmax Pallas kernel for TPU (never materializes the
  [S, S] score matrix). ``dot_product_attention(..., use_pallas=True)``
  dispatches to it on TPU.

The blockwise structure is the design seam for ring/sequence-parallel
attention (SURVEY.md §5 long-context): the same per-block accumulator runs
under ``shard_map`` with K/V blocks rotating over ICI
(``parallel/ring_attention.py``).
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite -inf stand-in: keeps fully-masked rows NaN-free

# Active sequence-parallel context (a stack so contexts nest): while set,
# ``dot_product_attention`` routes self-attention through the ppermute ring
# over the mesh's "seq" axis — the model code never changes (SURVEY.md §5
# long-context seam).
_SEQ_PARALLEL_CTX: list[tuple] = []


@contextlib.contextmanager
def sequence_parallel(
    mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str = "data",
    method: str = "ring",
):
    """Route zoo self-attention through sequence-parallel attention on
    ``mesh`` — ``method="ring"`` (K/V chunks rotate via ``ppermute``; any
    head count) or ``method="ulysses"`` (head↔sequence ``all_to_all``;
    needs ``num_heads % seq_axis_size == 0`` — see
    ``parallel.ulysses_attention`` for the trade).

    Usage (a dp×sp mesh; no model change):

    >>> with sequence_parallel(mesh):
    ...     result = fit(state, loss_fn, loader, mesh=mesh, ...)

    Dispatch per attention site (see ``dot_product_attention``): structured-
    mask self-attention whose sequence length divides the ``seq_axis`` size
    goes through the selected mechanism; cross-attention, decode steps, and
    dense-mask sites fall through to their usual paths.
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} has no '{seq_axis}' axis")
    if method not in ("ring", "ulysses"):
        raise ValueError(
            f"method must be 'ring' or 'ulysses', got {method!r}"
        )
    _SEQ_PARALLEL_CTX.append((mesh, seq_axis, batch_axis, method))
    try:
        yield
    finally:
        _SEQ_PARALLEL_CTX.pop()


def _active_seq_mesh():
    return _SEQ_PARALLEL_CTX[-1] if _SEQ_PARALLEL_CTX else None


# Forced implementation override for ``dot_product_attention``'s auto
# dispatch (a stack so contexts nest). None = auto (flash on TPU for
# structured masks, dense-XLA otherwise).
_FORCED_IMPL: list[str] = []


@contextlib.contextmanager
def attention_impl(impl: str):
    """Pin the structured-mask attention implementation inside the block:
    ``"dense"`` (materialized-[Sq,Sk] XLA path) or ``"flash"`` (blockwise
    Pallas kernel). Benchmarking/debugging hook — e.g. the long-context
    bench measures the flash kernel against the dense path it replaces
    (the reference's ``transformer.py:12-25`` core) at each sequence
    length. Sites the override cannot serve keep their rules: dense-mask
    calls never go to flash, and an active ``sequence_parallel`` context
    still wins.
    """
    if impl not in ("dense", "flash"):
        raise ValueError(f"impl must be 'dense' or 'flash', got {impl!r}")
    _FORCED_IMPL.append(impl)
    try:
        yield
    finally:
        _FORCED_IMPL.pop()


def multi_head_attention_weights(
    query: jnp.ndarray,
    key: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """``softmax(QKᵀ/√d)`` with boolean masking — the first half of
    ``scaled_dot_product`` (``transformer.py:17-24``), returned separately
    because the reference also returns the attention map."""
    d_k = query.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", query, key)
    scores = scores / jnp.sqrt(jnp.asarray(d_k, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    # Softmax in float32 regardless of compute dtype: bfloat16 exp/renorm
    # loses enough precision to hurt training at long sequence lengths.
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return weights.astype(query.dtype)


def scaled_dot_product_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    *,
    return_weights: bool = False,
):
    """Attention over ``[..., S, d]`` streams (typically ``[B, H, S, d]``).

    ``mask`` is boolean, True = attendable, broadcastable to
    ``[..., Sq, Sk]``. Query and key sequence lengths may differ (the
    cross-attention case the reference mis-handles, Q8).
    """
    weights = multi_head_attention_weights(query, key, mask)
    values = jnp.einsum("...qk,...kd->...qd", weights, value)
    if return_weights:
        return values, weights
    return values


def ragged_paged_attention(
    query: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    cur_k: jnp.ndarray | None = None,
    cur_v: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """One decode step of attention over a **paged** KV cache, ragged
    across the batch (Ragged Paged Attention, arxiv 2604.15464).

    Every request ``r`` attends its single query vector over the first
    ``lengths[r]`` cached positions, gathered page-by-page through its
    block table — so one compiled program serves any mix of sequence
    lengths and any batch occupancy (empty rows have ``lengths == 0``
    and a null block table).

    - ``query`` — ``[R, H, dh]``, one position per request row;
    - ``k_pages`` / ``v_pages`` — ``[num_pages, page_size, H*dh]``, the
      shared page store (page 0 is the never-allocated null page); may
      be ``int8`` when paired with ``k_scale``/``v_scale``;
    - ``block_table`` — ``[R, P]`` int32 page ids, zero-padded past each
      request's pages;
    - ``lengths`` — ``[R]`` int32 valid cached positions (0 = inactive);
    - ``k_scale`` / ``v_scale`` — optional ``[num_pages, page_size]``
      float32 dequantization scales for quantized page stores: slot
      ``(p, s)`` of the store dequantizes as ``pages[p, s] * scale[p, s]``.
      Scales ride the same block-table indirection as the pages, so a
      shared prefix page carries its scale to every reader;
    - ``cur_k`` / ``cur_v`` — optional ``[R, H*dh]``: the current step's
      K/V, attended unconditionally (the causal diagonal) *in addition*
      to the cached positions — this lets the caller run attention and
      the cache scatter in the same fused step without a read-after-write
      hazard on the page store. Always full-precision (never quantized).

    Dispatch mirrors ``dot_product_attention``: a Pallas TPU kernel
    whose block tables drive data-dependent page DMA when the layout
    allows it (``dh % 128 == 0``, ``page_size % 8 == 0`` for fp32 pages
    or ``page_size % 32 == 0`` for int8 pages — the int8 min-tile
    sublane count), otherwise a bit-equivalent gather + masked-softmax
    XLA path (the CPU tier-1 route, same fallback discipline as PR 7's
    native parsers). Both paths dequantize to float32 *before* the dot
    products, so kernel and fallback agree to float rounding.
    """
    num_rows, num_heads, head_dim = query.shape
    page_size = k_pages.shape[1]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if use_pallas is None:
        # int8 pages tile at (32, 128) on TPU, fp32 at (8, 128) — the
        # page_size divisibility gate follows the store dtype.
        min_sublanes = 32 if k_pages.dtype == jnp.int8 else 8
        use_pallas = (
            jax.default_backend() == "tpu"
            and head_dim % 128 == 0
            and page_size % min_sublanes == 0
        )
    if use_pallas:
        from machine_learning_apache_spark_tpu.ops.pallas_attention import (
            ragged_paged_attention_kernel,
        )

        return ragged_paged_attention_kernel(
            query, k_pages, v_pages, block_table, lengths,
            k_scale=k_scale, v_scale=v_scale,
            cur_k=cur_k, cur_v=cur_v, interpret=interpret,
        )
    # XLA fallback: gather the block-table pages into a dense [R, W, ...]
    # view and reuse the one masked-softmax core. Gathered-but-invalid
    # positions (page remainders, null pages) are masked, so they
    # contribute exactly +0.0 to the softmax sums.
    pages_per_req = block_table.shape[1]
    width = pages_per_req * page_size
    k = jnp.take(k_pages, block_table, axis=0)  # [R, P, page, D]
    v = jnp.take(v_pages, block_table, axis=0)
    if k_scale is not None:
        ks = jnp.take(k_scale, block_table, axis=0)  # [R, P, page]
        vs = jnp.take(v_scale, block_table, axis=0)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    k = k.reshape(num_rows, width, num_heads, head_dim).transpose(0, 2, 1, 3)
    v = v.reshape(num_rows, width, num_heads, head_dim).transpose(0, 2, 1, 3)
    valid = jnp.arange(width)[None, :] < lengths[:, None]  # [R, W]
    if cur_k is not None:
        cur_k = cur_k.reshape(num_rows, num_heads, 1, head_dim)
        cur_v = cur_v.reshape(num_rows, num_heads, 1, head_dim)
        k = jnp.concatenate([k, cur_k], axis=2)
        v = jnp.concatenate([v, cur_v], axis=2)
        valid = jnp.concatenate(
            [valid, jnp.ones((num_rows, 1), dtype=bool)], axis=1
        )
    out = scaled_dot_product_attention(
        query[:, :, None, :], k, v, valid[:, None, None, :]
    )[:, :, 0, :]
    if cur_k is None:
        # A fully-masked row (inactive: length 0, no current token) must
        # emit zeros like the kernel's l==0 finalize path, not the dense
        # softmax's uniform average of garbage V.
        out = jnp.where((lengths > 0)[:, None, None], out, 0.0)
    return out


def dot_product_attention(
    query: jnp.ndarray,
    key: jnp.ndarray,
    value: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    *,
    causal: bool = False,
    kv_valid: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Backend-dispatching attention entry point used by the model zoo.

    Masking comes in two forms:

    - dense ``mask`` (boolean, broadcastable to ``[..., Sq, Sk]``) — always
      takes the fused-XLA path (an arbitrary mask cannot stream through the
      blockwise kernel);
    - structured ``causal`` + ``kv_valid`` (``[B, S_k]`` per-key validity,
      the padding-mask case) — exactly the masks the zoo Transformer needs,
      streamed through the Pallas flash kernel on TPU without ever
      materializing ``[B, Sq, Sk]``.

    ``use_pallas=None`` auto-selects the flash kernel on TPU whenever the
    mask is structured-only.

    Under an active ``sequence_parallel(mesh)`` context, structured-mask
    *self-attention* (Sq == Sk, divisible by the seq axis) dispatches to
    ``parallel.ring_attention`` instead — K/V chunks rotate over ICI and no
    device ever holds the full sequence. Other sites (cross-attention,
    KV-cache decode, dense masks) keep their usual paths.
    """
    ctx = _active_seq_mesh()
    if (
        ctx is not None
        and mask is None
        and query.shape == key.shape == value.shape
        and query.shape[2] % ctx[0].shape[ctx[1]] == 0
        # Batch must also fill the mesh's batch axis (a ragged eval tail
        # batch, deliberately run unsharded by train.loop.evaluate, falls
        # through to the dense path instead of crashing shard_map).
        and query.shape[0] % ctx[0].shape.get(ctx[2], 1) == 0
    ):
        mesh, seq_axis, batch_axis, method = ctx
        if method == "ulysses":
            # A head count the seq axis cannot divide is a model-config
            # error, not a fall-through case: silently running the ring (or
            # dense) would misrepresent which mechanism executed.
            if query.shape[1] % mesh.shape[seq_axis]:
                raise ValueError(
                    f"sequence_parallel(method='ulysses') needs num_heads "
                    f"({query.shape[1]}) divisible by the {seq_axis!r} axis "
                    f"({mesh.shape[seq_axis]}); use method='ring'"
                )
            from machine_learning_apache_spark_tpu.parallel.ulysses_attention import (
                ulysses_attention,
            )

            return ulysses_attention(
                query, key, value, mesh,
                causal=causal, kv_valid=kv_valid,
                seq_axis=seq_axis, batch_axis=batch_axis,
            )
        from machine_learning_apache_spark_tpu.parallel.ring_attention import (
            ring_attention,
        )

        return ring_attention(
            query, key, value, mesh,
            causal=causal, kv_valid=kv_valid,
            seq_axis=seq_axis, batch_axis=batch_axis,
        )
    if use_pallas is None:
        if _FORCED_IMPL:
            use_pallas = _FORCED_IMPL[-1] == "flash" and mask is None
        else:
            use_pallas = jax.default_backend() == "tpu" and mask is None
    if use_pallas and mask is None:
        from machine_learning_apache_spark_tpu.ops.pallas_attention import (
            flash_attention,
        )

        return flash_attention(
            query, key, value, causal=causal, kv_valid=kv_valid
        )
    from machine_learning_apache_spark_tpu.ops.masks import (
        combine_masks,
        make_causal_mask,
    )

    if kv_valid is not None:
        mask = combine_masks(mask, kv_valid[:, None, None, :])
    if causal:
        mask = combine_masks(
            mask, make_causal_mask(query.shape[-2], key.shape[-2])
        )
    return scaled_dot_product_attention(query, key, value, mask)
