"""Attention mask builders.

Reference mask sites: look-ahead (causal) mask ``torch.tril(ones)==0`` unsqueezed
to ``[1,1,S,S]`` (``pytorch_machine_translator.py:102-104``) and padding masks
``(tensor != pad).unsqueeze(1).unsqueeze(2)`` (``pytorch_machine_translator.py:164-165``).

Convention here: boolean, ``True = position may be attended``. This is the
*inverse* of the reference's causal-mask polarity; the reference then applies
its mask additively (quirk Q9, SURVEY.md §2.5) which makes masking a near
no-op. The framework applies masks with ``where(mask, scores, -inf)`` — the
evident intent.
"""

from __future__ import annotations

import jax.numpy as jnp


def make_causal_mask(
    length: int, kv_length: int | None = None, *, dtype=jnp.bool_
) -> jnp.ndarray:
    """``[1, 1, Sq, Sk]`` causal mask: query i may attend keys <= i.

    The correct-semantics build of ``create_look_ahead_mask``
    (``pytorch_machine_translator.py:102-104``), polarity inverted to the
    True=attendable convention. With ``kv_length != length`` the diagonal is
    bottom-right aligned (the KV-cache decode convention: the last query row
    sees every key), matching the flash kernel.
    """
    kv_length = length if kv_length is None else kv_length
    offset = kv_length - length
    mask = jnp.tril(jnp.ones((length, kv_length), dtype=dtype), k=offset)
    return mask[None, None, :, :]


def make_padding_mask(tokens: jnp.ndarray, pad_id: int = 0) -> jnp.ndarray:
    """``[B, 1, 1, S]`` key-padding mask from token ids — the
    ``(tensor != pad).unsqueeze(1).unsqueeze(2)`` pattern
    (``pytorch_machine_translator.py:164-165``). Broadcasts over heads and
    query positions."""
    return (tokens != pad_id)[:, None, None, :]


def make_attention_mask(
    query_valid: jnp.ndarray, key_valid: jnp.ndarray
) -> jnp.ndarray:
    """``[B, 1, Sq, Sk]`` mask from per-position validity vectors.

    Supports *different* query/key lengths — the capability the reference's
    cross-attention forfeits by reusing the encoder's length for both streams
    (quirk Q8, ``transformer.py:180-188``).
    """
    return (query_valid[:, None, :, None] & key_valid[:, None, None, :])


def make_segment_mask(
    query_segments: jnp.ndarray, key_segments: jnp.ndarray
) -> jnp.ndarray:
    """``[B, 1, Sq, Sk]`` block-diagonal mask from per-token segment ids.

    A query may attend only keys of the SAME nonzero segment — the packing
    mask (``data.packing``): multiple sequences share one row without
    attending across each other, and segment id 0 (padding) attends/is
    attended by nothing.
    """
    q = query_segments[:, None, :, None]
    k = key_segments[:, None, None, :]
    return (q == k) & (q > 0) & (k > 0)


def combine_masks(*masks: jnp.ndarray | None) -> jnp.ndarray | None:
    """AND together broadcastable masks, skipping Nones (e.g. causal ∧ padding)."""
    present = [m for m in masks if m is not None]
    if not present:
        return None
    out = present[0]
    for m in present[1:]:
        out = out & m
    return out
