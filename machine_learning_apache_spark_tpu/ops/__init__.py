"""ops — attention core, masks, positional encodings.

The reference's hot inner kernel is ``scaled_dot_product`` + the mask builders
(``transformer.py:12-25``, ``pytorch_machine_translator.py:102-104``) — see
SURVEY.md §3.3. Implemented here once, with *correct* semantics (quirks Q8/Q9
fixed: boolean masks select, they are never added; query/key lengths are
independent).

Mask convention (flax-style): boolean, ``True = attendable``. The reference's
look-ahead mask uses the opposite polarity (True = masked,
``pytorch_machine_translator.py:102-104``) and then *adds* it (Q9); converting
at the boundary keeps the framework internally consistent.
"""

from machine_learning_apache_spark_tpu.ops.masks import (
    make_causal_mask,
    make_padding_mask,
    make_attention_mask,
    make_segment_mask,
    combine_masks,
)
from machine_learning_apache_spark_tpu.ops.positional import sinusoidal_encoding
from machine_learning_apache_spark_tpu.ops.attention import (
    attention_impl,
    dot_product_attention,
    ragged_paged_attention,
    scaled_dot_product_attention,
    multi_head_attention_weights,
    sequence_parallel,
)

__all__ = [
    "attention_impl",
    "dot_product_attention",
    "ragged_paged_attention",
    "make_causal_mask",
    "make_padding_mask",
    "make_attention_mask",
    "make_segment_mask",
    "combine_masks",
    "sinusoidal_encoding",
    "scaled_dot_product_attention",
    "multi_head_attention_weights",
    "sequence_parallel",
]
