"""mllib — the Spark-MLlib-parity baseline engine (reference C1).

The reference's ``mllib_multilayer_perceptron_classifier.py`` trains a
JVM-native MLP with breeze L-BFGS and evaluates accuracy via
``MulticlassClassificationEvaluator``. This module provides the same
estimator/transformer/evaluator API over the framework's own compute path —
the "other engine" axis of the reference's capability matrix (SURVEY.md §0).
"""

from machine_learning_apache_spark_tpu.mllib.classifier import (
    MultilayerPerceptronClassifier,
    MultilayerPerceptronClassificationModel,
)
from machine_learning_apache_spark_tpu.mllib.evaluation import (
    MulticlassClassificationEvaluator,
)

__all__ = [
    "MultilayerPerceptronClassifier",
    "MultilayerPerceptronClassificationModel",
    "MulticlassClassificationEvaluator",
]
