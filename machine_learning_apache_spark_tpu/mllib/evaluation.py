"""Evaluators — ``MulticlassClassificationEvaluator`` parity
(``mllib_multilayer_perceptron_classifier.py:44-48``)."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class MulticlassClassificationEvaluator:
    metricName: str = "accuracy"

    def evaluate(self, frame) -> float:
        """``frame`` is a ``PredictionFrame`` (or anything with
        ``select("prediction", "label")``)."""
        if self.metricName not in ("accuracy", "f1"):
            raise ValueError(f"unknown metric {self.metricName!r}")
        preds, labels = frame.select("prediction", "label")
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        if self.metricName == "accuracy":
            return float((preds == labels).mean())
        if self.metricName == "f1":
            # macro-averaged F1 (MLlib's default f1 is weighted; macro is the
            # deliberate, documented choice here)
            scores = []
            for c in np.unique(labels):
                tp = ((preds == c) & (labels == c)).sum()
                fp = ((preds == c) & (labels != c)).sum()
                fn = ((preds != c) & (labels == c)).sum()
                denom = 2 * tp + fp + fn
                scores.append(2 * tp / denom if denom else 0.0)
            return float(np.mean(scores))
        raise AssertionError("unreachable: metricName validated above")
