"""MLlib-parity MLP classifier: full-batch L-BFGS training.

Reference C1 (``mllib_multilayer_perceptron_classifier.py:32-39``):
``MultilayerPerceptronClassifier(layers=[4,5,4,3], maxIter=100, blockSize=30,
seed=1234, solver='l-bfgs', stepSize=0.03)`` then ``trainer.fit(train)`` /
``model.transform(test)``. MLlib's engine is breeze L-BFGS over the full
dataset, with per-iteration gradients computed as an RDD ``treeAggregate``
across executors (SURVEY.md §3.4); its MLP topology is sigmoid hidden layers
with a softmax output trained on cross-entropy.

TPU-first re-design: the *entire* L-BFGS run — all ``maxIter`` iterations,
each a full-batch value+grad plus the two-loop direction update and zoom
linesearch — is one compiled XLA program (``lax.scan`` over iterations via
``optax.lbfgs``). The treeAggregate becomes, on a multi-chip mesh, the same
compiled ``psum`` the DP train step uses; on one chip it is a single fused
reduction. No Python-loop-per-iteration, no host round-trips.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from machine_learning_apache_spark_tpu.data.frame import ArrayFrame
from machine_learning_apache_spark_tpu.models import MLP
from machine_learning_apache_spark_tpu.train.losses import cross_entropy
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclass
class PredictionFrame:
    """``model.transform(df)`` output: the input columns plus a
    ``prediction`` column (the MLlib DataFrame contract,
    ``mllib_multilayer_perceptron_classifier.py:45``)."""

    features: np.ndarray
    labels: np.ndarray
    predictions: np.ndarray

    def select(self, *cols: str) -> tuple[np.ndarray, ...]:
        mapping = {
            "features": self.features,
            "label": self.labels,
            "prediction": self.predictions,
        }
        return tuple(mapping[c] for c in cols)


@dataclass
class MultilayerPerceptronClassificationModel:
    """Fitted model — the transformer half of the estimator/transformer pair."""

    mlp: MLP
    params: dict
    loss_history: np.ndarray = field(repr=False, default=None)

    def transform(self, frame: ArrayFrame) -> PredictionFrame:
        features, labels = frame.arrays()
        logits = self.mlp.apply({"params": self.params}, jnp.asarray(features))
        preds = np.asarray(jnp.argmax(logits, axis=-1))
        return PredictionFrame(features, labels, preds)


@dataclass
class MultilayerPerceptronClassifier:
    """Estimator with the MLlib constructor surface
    (``mllib_multilayer_perceptron_classifier.py:32-35``).

    ``blockSize`` is accepted for parity; it is a JVM data-stacking
    performance knob with no XLA meaning (full-batch compute is already one
    fused program). ``stepSize`` applies only to ``solver='gd'`` — MLlib's
    own documented semantics (l-bfgs uses its linesearch instead). ``tol``
    is the convergence test on per-iteration loss improvement; once met, the
    remaining scan iterations freeze the carry (static trip count, compiled
    once).
    """

    layers: Sequence[int] = (4, 5, 4, 3)
    maxIter: int = 100
    blockSize: int = 30
    seed: int = 1234
    solver: str = "l-bfgs"
    stepSize: float = 0.03
    tol: float = 1e-6

    def setParams(self, **kw) -> "MultilayerPerceptronClassifier":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown param {k!r}")
            setattr(self, k, v)
        return self

    def fit(
        self, frame: ArrayFrame, mesh=None
    ) -> MultilayerPerceptronClassificationModel:
        """Full-batch L-BFGS fit; ``mesh`` shards the batch over ``"data"``.

        With a mesh, the per-iteration full-batch value+grad is computed
        with features/labels sharded across the ``"data"`` axis and params
        replicated — XLA's sharding propagation compiles the gradient
        reduction into a psum over ICI, the treeAggregate of MLlib's engine
        (``mllib_multilayer_perceptron_classifier.py:35-39`` via breeze
        L-BFGS over an RDD). Rows are zero-weight-padded to divisibility, so
        the sharded loss equals the single-device loss up to float32
        reduction order; L-BFGS amplifies that ~1e-8 seed chaotically near
        convergence, so final params are numerically equivalent, not
        bit-identical (tests/test_mllib.py::TestMeshFit pins the bound).
        """
        if self.solver.lower() not in ("l-bfgs", "lbfgs", "gd"):
            raise ValueError(f"unsupported solver {self.solver!r}")
        features, labels = frame.arrays()
        x = jnp.asarray(features, jnp.float32)
        y = jnp.asarray(labels)
        n = x.shape[0]
        weights = jnp.ones((n,), jnp.float32)

        if mesh is not None:
            from machine_learning_apache_spark_tpu.parallel.mesh import (
                DATA_AXIS,
                batch_sharding,
            )

            shards = mesh.shape[DATA_AXIS]
            pad = (-n) % shards
            if pad:
                x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
                y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
                weights = jnp.concatenate([weights, jnp.zeros((pad,), jnp.float32)])
            data_sharding = batch_sharding(mesh)
            x = jax.device_put(x, data_sharding)
            y = jax.device_put(y, data_sharding)
            weights = jax.device_put(weights, data_sharding)

        mlp = MLP(layers=tuple(self.layers))
        params = mlp.init(jax.random.key(self.seed), x[:1])["params"]
        if mesh is not None:
            from machine_learning_apache_spark_tpu.parallel.mesh import replicate

            params = replicate(mesh, params)

        def loss_fn(p):
            # Weighted-mean CE: padding rows carry zero weight, so the
            # sharded loss equals the unpadded single-device loss exactly.
            logits = mlp.apply({"params": p}, x)
            per_row = cross_entropy(logits, y, reduction="none")
            return jnp.sum(per_row * weights) / jnp.sum(weights)

        if self.solver.lower() == "gd":
            # MLlib's alternative solver ('gd' stepSize semantics).
            opt = optax.sgd(self.stepSize)

            def compute_update(p, s):
                value, grad = jax.value_and_grad(loss_fn)(p)
                updates, s = opt.update(grad, s, p)
                return value, updates, s

        else:
            opt = optax.lbfgs(memory_size=10)
            value_and_grad = optax.value_and_grad_from_state(loss_fn)

            def compute_update(p, s):
                value, grad = value_and_grad(p, state=s)
                updates, s = opt.update(
                    grad, s, p, value=value, grad=grad, value_fn=loss_fn
                )
                return value, updates, s

        def step(carry, _):
            p, s, prev, done = carry
            value, updates, s_new = compute_update(p, s)
            # MLlib's `tol` convergence test: stop when the loss improvement
            # falls below tol. Inside a fixed-length scan "stop" = freeze the
            # carry (the remaining iterations are no-ops the compiler can
            # still schedule; trip count stays static).
            done_now = done | (jnp.abs(prev - value) < self.tol)
            keep = lambda old, new: jax.tree.map(
                lambda a, b: jnp.where(done, a, b), old, new
            )
            p = keep(p, optax.apply_updates(p, updates))
            s = keep(s, s_new)
            return (p, s, value, done_now), (value, done_now)

        @jax.jit
        def run(p):
            # The whole optimizer — maxIter × (full-batch fwd+bwd + update
            # rule, incl. l-bfgs two-loop recursion and zoom linesearch) —
            # is ONE XLA program.
            carry = (p, opt.init(p), jnp.inf, jnp.asarray(False))
            (p, _, _, _), (hist, dones) = jax.lax.scan(
                step, carry, length=self.maxIter
            )
            return p, hist, dones

        params, history, dones = run(params)
        history = np.asarray(history)
        if history.size:
            iters = int((~np.asarray(dones)).sum())
            log.info(
                "%s: loss %.6f -> %.6f, %s after %d/%d iterations",
                self.solver, history[0], history[-1],
                "converged" if iters < self.maxIter else "stopped",
                min(iters + 1, self.maxIter), self.maxIter,
            )
        return MultilayerPerceptronClassificationModel(
            mlp=mlp, params=jax.device_get(params), loss_history=history
        )
