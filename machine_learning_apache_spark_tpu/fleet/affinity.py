"""Prefix-cache affinity table — which replica already holds this prompt.

The paged runtime's ``PrefixCache`` (``serving/kv_pages.py``) keys on
``tuple(token_ids)`` and a hit admits at zero prefill cost; across a
fleet that economics only survives if repeat prompts land on the replica
that paid for the prefill. This table maps
``prefix_digest(ids) -> ranks`` from two sources with different
latencies:

- **Routing memory** (instant): every dispatch records "digest went to
  rank" with an LRU bound + TTL. This is what makes the *second* request
  for a prompt stick before any scrape has run.
- **Scraped residency** (authoritative): each scrape tick replaces a
  rank's resident set with the digests its ``/statusz``
  ``prefix_cache.resident_digests`` actually reports. This corrects the
  routing memory's lies — evictions, replica restarts (a restarted
  replica scrapes back with an empty set and silently loses every stale
  claim) — at scrape-interval granularity.

``candidates(digest)`` is the union; the router intersects it with the
healthy set and falls back to least-loaded when it comes up empty.
Stdlib-only; the digest function itself lives in ``serving.kv_pages``
(the cache side must agree with the router side by construction).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from machine_learning_apache_spark_tpu.serving.kv_pages import prefix_digest

__all__ = ["AffinityTable", "prefix_digest"]


class AffinityTable:
    """Thread-safe digest → candidate-ranks map."""

    def __init__(
        self,
        *,
        memory_capacity: int = 4096,
        memory_ttl_s: float = 60.0,
        clock=time.monotonic,
    ):
        if memory_capacity < 0:
            raise ValueError(
                f"memory_capacity must be >= 0, got {memory_capacity}"
            )
        self.memory_capacity = memory_capacity
        self.memory_ttl_s = memory_ttl_s
        self.clock = clock
        self._lock = threading.Lock()
        # digest -> {rank: last_routed_t} (LRU over digests)
        self._memory: OrderedDict[str, dict[int, float]] = OrderedDict()
        # rank -> frozenset of scraped resident digests
        self._resident: dict[int, frozenset] = {}

    # -- writers -------------------------------------------------------------
    def note_routed(self, digest: str | None, rank: int) -> None:
        """Routing memory: ``digest`` was just dispatched to ``rank`` —
        by the time any response returns, that replica's cache holds (or
        is prefilling) the prefix."""
        if digest is None or self.memory_capacity == 0:
            return
        now = self.clock()
        with self._lock:
            entry = self._memory.get(digest)
            if entry is None:
                entry = self._memory[digest] = {}
            entry[rank] = now
            self._memory.move_to_end(digest)
            while len(self._memory) > self.memory_capacity:
                self._memory.popitem(last=False)

    def observe_scrape(self, rank: int, digests) -> None:
        """Authoritative residency for one rank — *replaces* the rank's
        previous set (an absent digest was evicted; an empty set after a
        restart revokes everything)."""
        with self._lock:
            self._resident[rank] = frozenset(digests)

    def forget_rank(self, rank: int) -> None:
        """Rank left the fleet (killed / drained): drop its residency
        and purge it from routing memory so dead ranks never surface as
        candidates."""
        with self._lock:
            self._resident.pop(rank, None)
            for entry in self._memory.values():
                entry.pop(rank, None)

    # -- readers -------------------------------------------------------------
    def candidates(self, digest: str | None) -> set[int]:
        """Ranks believed to hold ``digest``: scraped residency ∪
        unexpired routing memory."""
        if digest is None:
            return set()
        now = self.clock()
        out: set[int] = set()
        with self._lock:
            for rank, resident in self._resident.items():
                if digest in resident:
                    out.add(rank)
            entry = self._memory.get(digest)
            if entry:
                expired = [
                    r for r, t in entry.items()
                    if now - t > self.memory_ttl_s
                ]
                for r in expired:
                    del entry[r]
                if not entry:
                    self._memory.pop(digest, None)
                out.update(entry)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "memory_digests": len(self._memory),
                "memory_capacity": self.memory_capacity,
                "ranks_with_residency": sorted(self._resident),
                "resident_digests": {
                    r: len(d) for r, d in sorted(self._resident.items())
                },
            }
