"""Closed-loop fleet autoscaling — SLO burn drives replica count.

The fleet grew every piece of the loop except the loop itself: per-tier
SLO burn gauges at router and replica layers, health-aware routing with
drain semantics and a conservation ledger, per-rank restart supervision
in ``ReplicaGang``. :class:`FleetAutoscaler` closes it — a control step
driven by ``ScrapeLoop`` snapshots that resizes the replica set the way
PR 12's shrink-to-fit resizes a training gang: deliberately, one rank at
a time, with every decision written down.

Control law (evaluated once per scrape tick):

- **Scale up** when any tier's fleet burn EWMA crosses ``burn_up`` *or*
  mean in-flight per healthy replica crosses ``queue_up``, sustained for
  ``hysteresis_ticks`` consecutive ticks, outside the post-action
  ``cooldown_s`` window, below ``max_replicas``. One rank per decision.
- **Scale down** when burn is under ``burn_down`` *and* queue depth is
  under ``queue_down``, with the same hysteresis/cooldown discipline,
  above ``min_replicas``, and at most one drain in flight. The *coldest*
  healthy replica (lowest in-flight) is marked draining: its ``/healthz``
  flips to 503/"draining", the router penalty-boxes it, it finishes its
  in-flight work and exits, and the gang scrubs its sidecars so
  discovery — and with it the router's affinity/penalty state — forgets
  the rank. While the drain runs, the batch tier's admission cap is shed
  (``drain_batch_shed``) so the shrinking fleet's headroom protects
  interactive traffic.
- **Observed scale-down**: a rank that exhausted its restart budget is
  already gone; the autoscaler reaps its sidecars, recomputes the
  target, and logs the decision — preemption is a scale-down event, not
  a failure (the serving twin of elastic-gang shrink-to-fit).

Every decision — including ones *blocked* by cooldown, hysteresis, or
the min/max clamps — is a ``fleet.autoscaler`` annotation carrying its
inputs (burn, queue depth, live count, target, action), so Perfetto /
``trace_report`` can show *why* the fleet resized.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from machine_learning_apache_spark_tpu.fleet.scrape import (
    ReplicaSnapshot,
    fleet_slo_rollup,
)
from machine_learning_apache_spark_tpu.telemetry import events as _events
from machine_learning_apache_spark_tpu.utils import env as envcfg
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclass(frozen=True)
class AutoscaleConfig:
    """The control law's knobs; :meth:`from_env` reads the
    ``MLSPARK_AUTOSCALE_*`` contract registered in ``utils/env.py``."""

    min_replicas: int = 1
    max_replicas: int = 8
    burn_up: float = 0.1
    burn_down: float = 0.01
    queue_up: float = 4.0
    queue_down: float = 1.0
    hysteresis_ticks: int = 2
    cooldown_s: float = 5.0
    drain_deadline_s: float = 30.0
    drain_batch_shed: float = 0.5

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.burn_down > self.burn_up:
            raise ValueError(
                f"burn_down ({self.burn_down}) must be <= burn_up "
                f"({self.burn_up}) — the hysteresis band would invert"
            )
        if self.queue_down > self.queue_up:
            raise ValueError(
                f"queue_down ({self.queue_down}) must be <= queue_up "
                f"({self.queue_up}) — the hysteresis band would invert"
            )
        if self.hysteresis_ticks < 1:
            raise ValueError(
                f"hysteresis_ticks must be >= 1, got "
                f"{self.hysteresis_ticks}"
            )
        if not 0.0 < self.drain_batch_shed <= 1.0:
            raise ValueError(
                f"drain_batch_shed must be in (0, 1], got "
                f"{self.drain_batch_shed}"
            )

    @classmethod
    def from_env(cls) -> "AutoscaleConfig":
        return cls(
            min_replicas=envcfg.get_int("MLSPARK_AUTOSCALE_MIN_REPLICAS"),
            max_replicas=envcfg.get_int("MLSPARK_AUTOSCALE_MAX_REPLICAS"),
            burn_up=envcfg.get_float("MLSPARK_AUTOSCALE_BURN_UP"),
            burn_down=envcfg.get_float("MLSPARK_AUTOSCALE_BURN_DOWN"),
            queue_up=envcfg.get_float("MLSPARK_AUTOSCALE_QUEUE_UP"),
            queue_down=envcfg.get_float("MLSPARK_AUTOSCALE_QUEUE_DOWN"),
            hysteresis_ticks=envcfg.get_int(
                "MLSPARK_AUTOSCALE_HYSTERESIS_TICKS"
            ),
            cooldown_s=envcfg.get_float("MLSPARK_AUTOSCALE_COOLDOWN_S"),
            drain_deadline_s=envcfg.get_float(
                "MLSPARK_AUTOSCALE_DRAIN_DEADLINE_S"
            ),
            drain_batch_shed=envcfg.get_float(
                "MLSPARK_AUTOSCALE_DRAIN_BATCH_SHED"
            ),
        )


class FleetAutoscaler:
    """The control loop over a :class:`~machine_learning_apache_spark_tpu.
    launcher.replica_gang.ReplicaGang` (or anything with its membership
    API: ``live_ranks`` / ``add_rank`` / ``retire_rank`` / ``reap_rank``
    and ``exhausted``/``retired`` sets).

    :meth:`observe` is the unit-testable control step — feed it a
    snapshot map, it applies the law and pulls the gang's levers.
    :meth:`attach` registers it as a ``ScrapeLoop`` observer so it rides
    the router's scrape tick; :meth:`start` falls back to its own
    polling thread when no loop is available.
    """

    def __init__(
        self,
        gang,
        *,
        config: AutoscaleConfig | None = None,
        admission=None,
        clock=time.monotonic,
    ):
        self.gang = gang
        self.config = config or AutoscaleConfig.from_env()
        self.admission = admission  # FleetAdmission, for drain-time shed
        self.clock = clock
        self._lock = threading.Lock()
        self._up_ticks = 0
        self._down_ticks = 0
        self._cooldown_until = 0.0
        self._draining: set[int] = set()
        self._reaped: set[int] = set()
        self._shed_active = False
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.observed_scale_downs = 0
        self.decisions: list[dict] = []
        self.last_signals: dict = {}

    # -- lifecycle -----------------------------------------------------------
    def attach(self, scrape_loop) -> "FleetAutoscaler":
        """Ride an existing ``ScrapeLoop``: one scrape tick = one control
        step, same snapshots the router dispatches on."""
        scrape_loop.add_observer(self.observe)
        return self

    def start(
        self, snapshot_source, *, interval: float = 0.5
    ) -> "FleetAutoscaler":
        """Standalone mode: poll ``snapshot_source()`` on ``interval``
        from a daemon thread (for drivers without a router)."""
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._stop.clear()

        def _run() -> None:
            while not self._stop.is_set():
                try:
                    self.observe(snapshot_source())
                except Exception:
                    log.exception("autoscaler control step failed")
                self._stop.wait(interval)

        self._thread = threading.Thread(
            target=_run, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    # -- the control step ----------------------------------------------------
    def observe(self, snapshots: dict[int, ReplicaSnapshot]) -> dict:
        """One control step. Returns the signals it acted on (with the
        action taken) — the same payload every decision annotation
        carries."""
        with self._lock:
            signals = self._signals(snapshots)
            self.ticks += 1
            self._finish_drains(snapshots, signals)
            self._absorb_exhausted(signals)
            action = self._apply_law(snapshots, signals)
            signals["action"] = action
            self.last_signals = signals
        return signals

    # Callers hold ``self._lock`` for everything below.
    def _signals(self, snapshots: dict[int, ReplicaSnapshot]) -> dict:
        """Burn = worst tier EWMA across the fleet rollup; queue = mean
        in-flight per healthy replica. Draining replicas count toward
        neither — they're leaving, not load-bearing."""
        healthy = [
            s for s in snapshots.values() if s.healthy and not s.draining
        ]
        rollup = fleet_slo_rollup(snapshots)
        burn = max(
            (float(agg.get("max_ewma") or 0.0) for agg in rollup.values()),
            default=0.0,
        )
        loads = [s.load for s in healthy if s.load != float("inf")]
        queue_depth = (sum(loads) / len(loads)) if loads else 0.0
        live = sorted(self.gang.live_ranks())
        return {
            "burn": round(burn, 6),
            "queue_depth": round(queue_depth, 3),
            "healthy": len(healthy),
            "live": len(live),
            "draining": sorted(self._draining),
            "target": len(live),
        }

    def _finish_drains(
        self, snapshots: dict[int, ReplicaSnapshot], signals: dict
    ) -> None:
        """A draining rank that vanished from discovery (the gang scrubbed
        its sidecars after exit) has completed its retirement."""
        done = [
            r for r in self._draining
            if r not in snapshots or r in getattr(self.gang, "retired", ())
        ]
        for rank in done:
            self._draining.discard(rank)
            self.scale_downs += 1
            self._decide(
                "scale_down_complete", signals, rank=rank,
                target=signals["live"],
            )
        if not self._draining and self._shed_active:
            self._shed_active = False
            if self.admission is not None:
                try:
                    self.admission.unshed("batch")
                except Exception:
                    log.exception("batch unshed failed")

    def _absorb_exhausted(self, signals: dict) -> None:
        """Permanent rank death is an *observed* scale-down: reap the
        corpse's sidecars (discovery drops it, the router purges its
        routing state) and recompute the target. The ledger stays
        conserved — the victim's in-flight already terminated as
        failed/lost through the router's retry taxonomy."""
        exhausted = set(getattr(self.gang, "exhausted", ()))
        for rank in sorted(exhausted - self._reaped):
            if self.gang.reap_rank(rank):
                self._reaped.add(rank)
                self.observed_scale_downs += 1
                live = len(self.gang.live_ranks())
                target = max(self.config.min_replicas,
                             min(self.config.max_replicas, live))
                signals["live"] = live
                signals["target"] = target
                self._decide(
                    "observed_scale_down", signals, rank=rank,
                    target=target,
                )

    def _apply_law(
        self, snapshots: dict[int, ReplicaSnapshot], signals: dict
    ) -> str:
        cfg = self.config
        burn, queue = signals["burn"], signals["queue_depth"]
        live = signals["live"]
        hot = burn >= cfg.burn_up or queue >= cfg.queue_up
        cold = burn <= cfg.burn_down and queue <= cfg.queue_down
        if hot:
            self._up_ticks += 1
            self._down_ticks = 0
        elif cold:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = self._down_ticks = 0
            return "steady"
        now = self.clock()
        if hot:
            if self._up_ticks < cfg.hysteresis_ticks:
                return "hold_hysteresis"
            if now < self._cooldown_until:
                return self._decide("hold_cooldown", signals,
                                    target=live + 1)
            if live >= cfg.max_replicas:
                return self._decide("hold_at_max", signals, target=live)
            return self._scale_up(signals)
        if self._down_ticks < cfg.hysteresis_ticks:
            return "hold_hysteresis"
        if now < self._cooldown_until:
            return self._decide("hold_cooldown", signals, target=live - 1)
        if self._draining:
            return "hold_draining"  # one drain at a time
        if live <= cfg.min_replicas:
            return self._decide("hold_at_min", signals, target=live)
        return self._scale_down(snapshots, signals)

    def _scale_up(self, signals: dict) -> str:
        target = signals["live"] + 1
        try:
            rank = self.gang.add_rank()
        except Exception:
            log.exception("scale-up spawn failed")
            return self._decide("scale_up_failed", signals, target=target)
        self.scale_ups += 1
        self._cooldown_until = self.clock() + self.config.cooldown_s
        self._up_ticks = 0
        return self._decide("scale_up", signals, rank=rank, target=target)

    def _scale_down(
        self, snapshots: dict[int, ReplicaSnapshot], signals: dict
    ) -> str:
        target = signals["live"] - 1
        live = set(self.gang.live_ranks())
        # Coldest live replica: fewest requests in flight loses its job.
        candidates = sorted(
            (s for s in snapshots.values()
             if s.rank in live and s.healthy and not s.draining),
            key=lambda s: (s.load, s.rank),
        )
        if not candidates:
            return self._decide("hold_no_candidate", signals, target=target)
        if len(candidates) == 1:
            # Draining the only healthy replica would leave zero serving
            # capacity while warming/unhealthy ranks are still coming up
            # — hold until a second replica is healthy enough to carry
            # the load the victim gives back.
            return self._decide("hold_last_healthy", signals, target=target)
        victim = candidates[0].rank
        if not self.gang.retire_rank(
            victim, drain=True, deadline_s=self.config.drain_deadline_s
        ):
            return self._decide("hold_no_candidate", signals, target=target,
                                rank=victim)
        self._draining.add(victim)
        self._cooldown_until = self.clock() + self.config.cooldown_s
        self._down_ticks = 0
        if self.admission is not None and not self._shed_active:
            # Batch-first shedding: the drain temporarily removes a
            # replica's worth of capacity — take it out of the batch
            # tier's admission budget, never out of interactive's.
            try:
                self.admission.shed("batch", self.config.drain_batch_shed)
                self._shed_active = True
            except Exception:
                log.exception("batch shed failed")
        return self._decide(
            "scale_down_start", signals, rank=victim, target=target,
        )

    def _decide(self, action: str, signals: dict, **extra) -> str:
        """The decision log: one ``fleet.autoscaler`` annotation per
        decision, always carrying its inputs."""
        record = {
            "action": action,
            "burn": signals["burn"],
            "queue_depth": signals["queue_depth"],
            "healthy": signals["healthy"],
            "live": signals["live"],
            "target": extra.pop("target", signals["target"]),
            "wall": round(time.time(), 3),
            **extra,
        }
        self.decisions.append(record)
        try:
            _events.annotate("fleet.autoscaler", **record)
        except Exception:
            pass  # telemetry must never break the control loop
        log.info(
            "autoscale %s: burn=%.4f queue=%.2f live=%d target=%d %s",
            action, record["burn"], record["queue_depth"], record["live"],
            record["target"], extra or "",
        )
        return action

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "observed_scale_downs": self.observed_scale_downs,
                "draining": sorted(self._draining),
                "shed_active": self._shed_active,
                "decisions": len(self.decisions),
                "last": dict(self.last_signals),
                "config": {
                    "min_replicas": self.config.min_replicas,
                    "max_replicas": self.config.max_replicas,
                    "burn_up": self.config.burn_up,
                    "burn_down": self.config.burn_down,
                    "queue_up": self.config.queue_up,
                    "queue_down": self.config.queue_down,
                    "hysteresis_ticks": self.config.hysteresis_ticks,
                    "cooldown_s": self.config.cooldown_s,
                    "drain_deadline_s": self.config.drain_deadline_s,
                    "drain_batch_shed": self.config.drain_batch_shed,
                },
            }
