"""Replica data plane — one serving engine behind one HTTP front door.

Each fleet rank runs a :class:`ReplicaServer` around its
``ServingEngine``: ``POST /v1/generate`` maps the engine's request
contract onto HTTP status codes the router can dispatch around —

- **200** — translation complete; body carries text, trace id, token
  count.
- **429** — the replica queue pushed back (``Backpressure``); body and
  ``Retry-After`` header carry the queue's own estimate. The router may
  try another replica.
- **503** — the engine is degraded (mid-quarantine) or stopping; the
  router must *drain* around this replica until ``/healthz`` recovers.
- **504** — the request's deadline expired inside this replica.
- **500** — the decode step itself failed (``InternalError``).

``POST /v1/cancel`` is the hedging router's remote reap: keyed by the
router-minted trace id (the one the traceparent header carried in and
the engine's ``RequestTrace`` adopted), it force-expires the matching
in-flight request — still-queued work dies in the next queue sweep,
mid-decode work at the engine's next between-launch deadline sweep,
freeing its KV pages and launch slot. The abandoned handler thread then
answers 504 to a caller that already took the winning response.

The handler is also the application point for the ``wire`` fault family
(``utils.faults.wire_fault``): delay / black-hole / torn-response /
corrupt-body / slow-drip, matched by deterministic (rank,
request-ordinal) coordinates — the router's retry taxonomy drilled at
the exact layer it claims to handle.

The same server answers the observability plane's GET endpoints
(``/healthz``, ``/statusz``, ``/metrics``, ``/flightz``) by delegating
to ``telemetry.http``'s payload functions, so the router's scrape loop
judges the *data-plane* socket — a replica whose server wedged can't
look healthy through a separate port.

Discovery follows the telemetry sidecar idiom: the bound port lands in
``fleet_rank<k>.json`` (``MLSPARK_FLEET_DIR``, defaulting to the
telemetry dir). :func:`serve_replica` is the launcher-gang worker body:
build engine, serve, poll for the ``fleet_stop`` marker, drain, report.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from machine_learning_apache_spark_tpu.serving.queue import (
    Backpressure,
    DeadlineExceeded,
)
from machine_learning_apache_spark_tpu.telemetry import events as _events
from machine_learning_apache_spark_tpu.telemetry import http as _thttp
from machine_learning_apache_spark_tpu.telemetry import spans as _spans
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as _tracectx,
)
from machine_learning_apache_spark_tpu.utils import env as envcfg
from machine_learning_apache_spark_tpu.utils import faults as _faults
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: Router-visible generate timeout padding beyond the request deadline.
RESULT_GRACE_S = 10.0
STOP_MARKER = "fleet_stop"


def fleet_sidecar_name(rank: int) -> str:
    return f"fleet_rank{rank}.json"


def drain_marker_name(rank: int) -> str:
    """Per-rank drain marker: the autoscaler (via ``ReplicaGang.
    retire_rank``) drops this file in the fleet dir to tell exactly one
    replica to stop accepting work, finish its in-flight, and exit. The
    JSON body carries the drain ``deadline`` (epoch seconds) past which
    the replica exits regardless."""
    return f"fleet_drain_rank{rank}"


def write_fleet_sidecar(
    port: int, directory: str | None = None, rank: int | None = None
) -> str | None:
    """Publish the data-plane port for the router's discovery — same
    atomic tmp+replace discipline as ``telemetry.http.write_port_sidecar``."""
    d = directory or fleet_dir()
    if not d:
        return None
    if rank is None:
        r = _events._env_rank()
        rank = 0 if r is None else r
    path = os.path.join(d, fleet_sidecar_name(rank))
    payload = {
        "port": port,
        "rank": rank,
        "pid": os.getpid(),
        "wall": round(time.time(), 3),
    }
    try:
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def fleet_dir() -> str | None:
    """Where fleet sidecars and the stop marker live:
    ``MLSPARK_FLEET_DIR`` > telemetry dir."""
    return envcfg.get_str("MLSPARK_FLEET_DIR") or _events.telemetry_dir()


class _ReplicaHandler(BaseHTTPRequestHandler):
    server_version = "mlspark-fleet-replica"

    def log_message(self, *args) -> None:  # noqa: ARG002 — not log spam
        pass

    # -- data plane ----------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 — http.server API
        owner: ReplicaServer = self.server.replica  # type: ignore[attr-defined]
        if self.path == "/v1/cancel":
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length).decode("utf-8"))
                trace_id = body["trace_id"]
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": f"bad request body: {e!r}"})
                return
            code, payload = owner.cancel(trace_id)
            self._reply(code, payload)
            return
        if self.path != "/v1/generate":
            self._reply(404, {"error": f"no endpoint {self.path!r}"})
            return
        # Wire fault injection happens HERE, at the socket, before the
        # engine sees anything: the ordinal is this server's zero-based
        # exchange count, so a drill pins a fault to exactly one exchange
        # on exactly one rank.
        ordinal = owner.next_wire_ordinal()
        spec = _faults.wire_fault(rank=owner.rank, req=ordinal)
        if spec is not None:
            owner.note_wire_fault(spec, ordinal)
            if spec.action == "delay" and spec.ms:
                time.sleep(spec.ms / 1000.0)
            elif spec.action == "blackhole":
                # Swallow the exchange: drain the request so the client
                # isn't stuck writing, answer nothing, hang up. The
                # router classifies this "lost" — terminal, no replay.
                length = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(length)
                self.close_connection = True
                return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length).decode("utf-8"))
            text = body["text"]
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, {"error": f"bad request body: {e!r}"})
            return
        code, payload = owner.generate(
            text,
            deadline_s=body.get("deadline_s"),
            tier=body.get("tier"),
            tenant=body.get("tenant"),
            traceparent=self.headers.get("traceparent"),
        )
        headers = {}
        if code == 429 and payload.get("retry_after") is not None:
            headers["Retry-After"] = f"{payload['retry_after']:.3f}"
        if spec is not None and spec.action in ("torn", "corrupt", "drip"):
            self._reply_wire(spec, code, payload, headers)
            return
        self._reply(code, payload, headers)

    # -- observability plane (delegated) -------------------------------------
    def do_GET(self) -> None:  # noqa: N802 — http.server API
        try:
            if self.path.startswith("/metrics"):
                self._reply_raw(
                    200, _thttp.metrics_text(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif self.path.startswith("/healthz"):
                payload, healthy = _thttp.healthz()
                owner: ReplicaServer = self.server.replica  # type: ignore[attr-defined]
                if owner.draining:
                    # Drain outranks the engine's own verdict: the scrape
                    # plane must see "draining" (a deliberate, live exit)
                    # rather than "degraded" (a failure), so membership
                    # accounting doesn't count the retirement as an
                    # outage.
                    payload = dict(payload)
                    payload["status"] = "draining"
                    healthy = False
                self._reply(200 if healthy else 503, payload)
            elif self.path.startswith("/flightz"):
                self._reply(200, _thttp.flightz())
            elif self.path.startswith("/tracez"):
                m = re.search(r"(?:^|[?&])id=([0-9a-fA-F]+)", self.path)
                self._reply(
                    200, _thttp.tracez(m.group(1).lower() if m else None)
                )
            elif self.path.startswith("/statusz") or self.path == "/":
                self._reply(200, _thttp.statusz())
            else:
                self._reply(404, {"error": f"no endpoint {self.path!r}"})
        except Exception as e:  # noqa: BLE001 — a scrape must not kill the thread
            self._reply(500, {"error": repr(e)})

    # -- plumbing ------------------------------------------------------------
    def _reply(
        self, code: int, payload: dict, headers: dict | None = None
    ) -> None:
        self._reply_raw(
            code, json.dumps(payload) + "\n", "application/json", headers
        )

    def _reply_raw(
        self,
        code: int,
        body: str,
        ctype: str,
        headers: dict | None = None,
    ) -> None:
        data = body.encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up — its in-flight request, its loss

    def _reply_wire(
        self, spec, code: int, payload: dict, headers: dict | None = None
    ) -> None:
        """Deliver a real response through an injected wire fault —
        the response-side half of the ``wire`` family."""
        data = (json.dumps(payload) + "\n").encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if spec.action == "torn":
                # Full Content-Length, half a body, then hang up: the
                # client sees a short read — indistinguishable from a
                # replica dying mid-response ("lost", terminal).
                self.wfile.write(data[: max(1, len(data) // 2)])
                self.wfile.flush()
                self.close_connection = True
            elif spec.action == "corrupt":
                # Right length, unparseable content: the router's JSON
                # decode fails — also "lost", also terminal.
                self.wfile.write(b"#" * (len(data) - 1) + b"\n")
            elif spec.action == "drip":
                # Trickle the body out over ~spec.ms total — the slow
                # response a hedge should beat without any hard failure.
                chunks = [data[i:i + 16] for i in range(0, len(data), 16)]
                pause = (spec.ms / 1000.0) / max(1, len(chunks))
                for chunk in chunks:
                    self.wfile.write(chunk)
                    self.wfile.flush()
                    time.sleep(pause)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up — its in-flight request, its loss


class ReplicaServer:
    """The HTTP front door over one started ``ServingEngine``."""

    def __init__(
        self,
        engine,
        *,
        rank: int | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
        health_fn=None,
    ):
        self.engine = engine
        r = _events._env_rank()
        self.rank = rank if rank is not None else (0 if r is None else r)
        # Injectable health for tests; production uses the engine's own
        # /healthz verdict (worker alive + quarantine recovered).
        self._health_fn = health_fn or (
            lambda: engine._health_snapshot().get("healthy", False)
        )
        self._httpd = ThreadingHTTPServer((host, port), _ReplicaHandler)
        self._httpd.daemon_threads = True
        self._httpd.replica = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None
        self.sidecar_path: str | None = None
        self._lock = threading.Lock()
        self._draining = False
        self.requests = 0
        self.completed = 0
        self.rejected = 0
        self.refused_503 = 0
        self.failed = 0
        self.expired = 0
        self.cancelled = 0
        self.wire_faults = 0
        self._wire_ordinal = 0
        # trace_id -> in-flight ServeRequest: the /v1/cancel key space.
        # Entries live exactly as long as a handler thread waits on the
        # engine future — insert after submit, pop in its finally.
        self._inflight: dict[str, object] = {}

    @property
    def draining(self) -> bool:
        return self._draining

    def next_wire_ordinal(self) -> int:
        """Zero-based ordinal of the next ``/v1/generate`` exchange —
        the ``req`` coordinate wire fault specs match against."""
        with self._lock:
            n = self._wire_ordinal
            self._wire_ordinal += 1
            return n

    def note_wire_fault(self, spec, ordinal: int) -> None:
        with self._lock:
            self.wire_faults += 1
        _events.annotate(
            "fleet.wire_fault", rank=self.rank, action=spec.action,
            req=ordinal, key=spec.key,
        )

    def cancel(self, trace_id: str) -> tuple[int, dict]:
        """Remote reap (the hedging router's loser-cancellation path):
        force-expire the in-flight request carrying this router-minted
        trace id by pulling its deadline to *now*. Still-queued work dies
        in the immediate queue sweep; mid-decode work at the engine's
        next between-launch deadline sweep — either way its pages and
        slot free, the engine ledger books ``expired``, and the waiting
        handler thread answers 504 to a caller that no longer cares."""
        with self._lock:
            req = self._inflight.get(trace_id)
        if req is None:
            return 404, {
                "cancelled": False,
                "rank": self.rank,
                "error": "no in-flight request with that trace id",
            }
        req.deadline = self.engine.clock()
        with self._lock:
            self.cancelled += 1
        self.engine.queue.expire_now()
        _events.annotate(
            "fleet.replica_cancel", rank=self.rank, trace_id=trace_id
        )
        return 200, {
            "cancelled": True, "rank": self.rank, "trace_id": trace_id,
        }

    def set_draining(self, flag: bool = True) -> None:
        """Flip the front door to refuse-new-work mode: ``/healthz``
        answers 503 with status "draining" and ``generate`` refuses with
        503, while already-accepted requests run to completion."""
        if flag and not self._draining:
            _events.annotate("fleet.replica_draining", rank=self.rank,
                             port=self.port)
        self._draining = bool(flag)

    # -- lifecycle -----------------------------------------------------------
    def start(self, *, directory: str | None = None) -> "ReplicaServer":
        if self._thread is not None:
            raise RuntimeError("replica server already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name=f"fleet-replica-{self.rank}",
            daemon=True,
        )
        self._thread.start()
        self.sidecar_path = write_fleet_sidecar(
            self.port, directory=directory, rank=self.rank
        )
        _events.beacon_update(fleet_port=self.port)
        _events.annotate("fleet.replica_started", rank=self.rank,
                         port=self.port)
        return self

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._httpd.shutdown()
        t.join(10.0)
        self._httpd.server_close()
        self._thread = None
        if self.sidecar_path:
            try:
                os.unlink(self.sidecar_path)
            except OSError:
                pass

    def __enter__(self) -> "ReplicaServer":
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path (handler threads call in) ------------------------------
    def generate(
        self,
        text: str,
        *,
        deadline_s: float | None = None,
        tier: str | None = None,
        tenant: str | None = None,
        traceparent: str | None = None,
    ) -> tuple[int, dict]:
        """One routed request, handler thread. The router's traceparent
        header (when present and well-formed) re-activates its trace on
        this thread for the whole replica-side lifetime: the
        ``fleet.replica`` span records this hop (``remote_parent`` is
        the router attempt's span id — the cross-process edge
        ``traceview`` draws a flow arrow over), and the engine adopts
        the context at submit so the queue/decode spans stitch in."""
        ctx = _tracectx.parse_traceparent(traceparent)
        attrs = {"rank": self.rank, "tier": tier}
        if ctx is not None:
            attrs["remote_parent"] = ctx.span_id
        with _tracectx.use(ctx), _spans.span("fleet.replica", **attrs):
            return self._generate_inner(
                text, deadline_s=deadline_s, tier=tier, tenant=tenant
            )

    def _generate_inner(
        self,
        text: str,
        *,
        deadline_s: float | None,
        tier: str | None,
        tenant: str | None,
    ) -> tuple[int, dict]:
        with self._lock:
            self.requests += 1
        if self._draining:
            with self._lock:
                self.refused_503 += 1
            return 503, {
                "error": "replica draining",
                "rank": self.rank,
            }
        if not self._healthy():
            # Drain signal: degraded replicas refuse *before* the queue,
            # so a quarantined engine's backlog drains while new traffic
            # flows to healthy replicas.
            with self._lock:
                self.refused_503 += 1
            return 503, {
                "error": "replica degraded",
                "rank": self.rank,
            }
        try:
            req = self.engine.submit(text, deadline_s=deadline_s, tier=tier)
        except Backpressure as e:
            with self._lock:
                self.rejected += 1
            return 429, {
                "error": "backpressure",
                "retry_after": e.retry_after,
                "depth": e.depth,
                "rank": self.rank,
            }
        except ValueError as e:
            with self._lock:
                self.failed += 1
            return 400, {"error": str(e), "rank": self.rank}
        except RuntimeError as e:  # EngineStopped / not started
            with self._lock:
                self.refused_503 += 1
            return 503, {"error": repr(e), "rank": self.rank}
        trace_id = req.trace.trace_id
        with self._lock:
            self._inflight[trace_id] = req
        timeout = (deadline_s or 120.0) + RESULT_GRACE_S
        try:
            out = req.result(timeout=timeout)
        except DeadlineExceeded as e:
            # Deadline burn-down or a remote /v1/cancel — either way the
            # engine booked ``expired``; mirror that here, not ``failed``.
            with self._lock:
                self.expired += 1
            return 504, {"error": str(e), "rank": self.rank,
                         "trace_id": trace_id}
        except Exception as e:  # noqa: BLE001 — InternalError, stop, timeout
            with self._lock:
                self.failed += 1
            return 500, {"error": repr(e), "rank": self.rank,
                         "trace_id": trace_id}
        finally:
            with self._lock:
                self._inflight.pop(trace_id, None)
        with self._lock:
            self.completed += 1
        return 200, {
            "text": out,
            "rank": self.rank,
            "trace_id": req.trace.trace_id,
            "tier": tier,
            "tenant": tenant,
            "tokens": len(self.engine.translator.trg_pipe.ragged([out])[0]),
        }

    def _healthy(self) -> bool:
        try:
            return bool(self._health_fn())
        except Exception:
            return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "rank": self.rank,
                "port": self.port,
                "requests": self.requests,
                "completed": self.completed,
                "rejected": self.rejected,
                "refused_503": self.refused_503,
                "failed": self.failed,
                "expired": self.expired,
                "cancelled": self.cancelled,
                "wire_faults": self.wire_faults,
            }


def serve_replica(
    translator,
    engine_knobs: dict | None = None,
    *,
    rank: int | None = None,
    directory: str | None = None,
    port: int | None = None,
    max_s: float = 3600.0,
    poll_s: float = 0.1,
) -> dict:
    """Gang-worker body: start engine + data plane, publish the sidecar,
    serve until the driver drops a ``fleet_stop`` marker in the fleet
    dir (or ``max_s`` passes), then drain and report. Importable by
    reference — the replica-gang launch mode runs exactly this.

    Engine knobs resolve arg > env > default inside ``translator.serve``
    — so a fleet driver can set a replica's KV discipline either
    explicitly (``engine_knobs={"kv_mode": ..., "kv_dtype": ...}``) or
    through the Distributor env contract (``MLSPARK_SERVE_KV_MODE`` /
    ``MLSPARK_SERVE_KV_DTYPE`` exported to every rank)."""
    d = directory or fleet_dir() or "."
    if port is None:
        port = envcfg.get_int("MLSPARK_FLEET_PORT")
    knobs = dict(engine_knobs or {})
    engine = translator.serve(start=False, **knobs)
    stop_marker = os.path.join(d, STOP_MARKER)
    with engine:
        server = ReplicaServer(engine, rank=rank, port=port)
        server.start(directory=d)
        drain_marker = os.path.join(d, drain_marker_name(server.rank))
        try:
            _events.beacon_update(phase="serving")
            deadline = time.monotonic() + max_s
            while time.monotonic() < deadline:
                if os.path.exists(stop_marker):
                    break
                if not server.draining and os.path.exists(drain_marker):
                    # Retirement order from the autoscaler: refuse new
                    # work, let in-flight finish, then exit — or exit at
                    # the marker's wall-clock deadline, whichever first.
                    server.set_draining(True)
                if server.draining:
                    in_flight = engine.metrics.ledger().get("in_flight") or 0
                    if in_flight <= 0:
                        break
                    if time.time() >= _read_drain_deadline(drain_marker):
                        break
                time.sleep(poll_s)
            stats = server.stats()
        finally:
            server.stop()
        ledger = engine.metrics.ledger()
    if server.draining:
        _events.annotate("fleet.replica_retired", rank=server.rank,
                         in_flight=ledger.get("in_flight"))
    return {"server": stats, "ledger": ledger, "drained": server.draining}


def _read_drain_deadline(path: str) -> float:
    """Wall-clock deadline carried by a drain marker; ``inf`` when the
    marker is empty or torn (the in-flight-zero exit still applies, and
    the gang's supervisor holds its own kill backstop)."""
    try:
        with open(path) as f:
            payload = json.load(f)
        return float(payload["deadline"])
    except (OSError, ValueError, KeyError, TypeError):
        return float("inf")
