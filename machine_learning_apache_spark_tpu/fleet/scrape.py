"""Scrape data plane — the fleet's live view of every replica.

``tools/gang_status.py`` grew the original one-shot scrape over each
rank's ``/healthz`` + ``/statusz``; this module promotes that logic into
a reusable data plane (the tool now imports it back). Two layers:

- :func:`scrape` / :func:`snapshot_replica` — one endpoint / one replica,
  with **retry + backoff** baked in. The sidecar-discovery race lives
  here: a replica writes its ``fleet_rank<k>.json`` (or
  ``http_rank<k>.json``) sidecar in the same instant its server binds,
  so a scraper that reads the sidecar a moment early gets connection-
  refused once — that must read as "try again shortly", never as a
  cached "unreachable".
- :class:`ScrapeLoop` — a daemon thread that re-discovers sidecars and
  re-snapshots every replica on an interval, maintaining the
  ``{rank: ReplicaSnapshot}`` map the router's dispatch decisions read.
  Discovery is re-run every tick on purpose: a restarted replica comes
  back on a *new* ephemeral port and overwrites its sidecar, and the
  loop must follow it there without being told.

Everything here is stdlib-only and JAX-free — a router process never
needs the framework imported.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field

SIDECAR_RE = re.compile(r"(?:fleet|http)_rank(\d+)\.json$")


def scrape(
    port: int,
    path: str,
    timeout: float = 2.0,
    *,
    retries: int = 0,
    backoff: float = 0.1,
) -> dict | None:
    """GET one endpoint off a replica's local plane; None on failure (a
    dead replica must not kill the whole table). A 503 body is still a
    payload — that's ``/healthz`` saying "degraded", which the caller
    wants verbatim. ``retries`` re-attempts connection-level failures
    with exponential backoff (the sidecar-before-bind race shows up as
    exactly one connection-refused); HTTP-level errors don't retry —
    the server answered, so there is nothing to wait out."""
    url = f"http://127.0.0.1:{port}{path}"
    delay = backoff
    for attempt in range(retries + 1):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode("utf-8"))
            except Exception:
                return None
        except Exception:
            if attempt == retries:
                return None
            time.sleep(delay)
            delay = min(delay * 2, 2.0)
    return None


def find_fleet_sidecars(directory: str) -> dict[int, dict]:
    """``{rank: payload}`` for every ``fleet_rank<k>.json`` /
    ``http_rank<k>.json`` in a directory, fleet sidecars winning when a
    rank has both (the data-plane port serves the observability
    endpoints too, and it's the one the router must judge healthy)."""
    out: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(directory, "*_rank*.json"))):
        m = SIDECAR_RE.search(os.path.basename(path))
        if not m:
            continue
        rank = int(m.group(1))
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue  # torn write — next tick gets it
        if not (isinstance(payload, dict) and "port" in payload):
            continue
        is_fleet = os.path.basename(path).startswith("fleet_")
        if is_fleet or rank not in out:
            payload = dict(payload)
            payload["kind"] = "fleet" if is_fleet else "http"
            out[rank] = payload
    return dict(sorted(out.items()))


@dataclass
class ReplicaSnapshot:
    """One replica's scraped state — everything dispatch needs, nothing
    it has to re-parse. ``healthy`` means "accepts new requests":
    /healthz answered 200. A degraded (503) or unreachable replica keeps
    its last-known load fields so operators can still see it, but the
    router sends it nothing."""

    rank: int
    port: int
    healthy: bool = False
    status: str = "unreachable"  # ok | degraded | draining | unreachable
    queue_depth: int | None = None
    in_flight: int | None = None
    active_rows: int | None = None
    tokens_per_sec: float | None = None
    tokens_out: int | None = None
    completed: int | None = None
    occupancy: float | None = None
    # Per-tier SLO burn snapshots from the replica's serving metrics
    # ({tier: {"ewma": ..., "window_rate": ..., ...}}) — the raw material
    # for :func:`fleet_slo_rollup`.
    slo: dict = field(default_factory=dict)
    prefix_digests: frozenset = frozenset()
    prefix_stats: dict = field(default_factory=dict)
    scraped_at: float = 0.0
    consecutive_failures: int = 0

    @property
    def draining(self) -> bool:
        """Deliberately refusing new work while it retires its in-flight
        (the autoscaler's scale-down protocol). Unhealthy for dispatch —
        the router must not send it anything — but *not* a failure
        signal: a draining replica answered its scrape, so it never
        burns the unreachable grace, and membership accounting counts it
        as a live, leaving rank rather than a dead one."""
        return self.status == "draining"

    @property
    def load(self) -> float:
        """Least-loaded score: requests this replica already owes work
        for. in_flight (queued + decoding) when the serving section
        answered; a replica that exposes no serving section scores by
        queue_depth alone; unknown sorts last."""
        if self.in_flight is not None:
            return float(self.in_flight)
        if self.queue_depth is not None:
            return float(self.queue_depth)
        return float("inf")


def snapshot_replica(
    rank: int,
    port: int,
    *,
    timeout: float = 2.0,
    retries: int = 2,
) -> ReplicaSnapshot:
    """Scrape one replica's ``/healthz`` + ``/statusz`` into a snapshot."""
    snap = ReplicaSnapshot(rank=rank, port=port, scraped_at=time.monotonic())
    health = scrape(port, "/healthz", timeout=timeout, retries=retries)
    if health is None:
        return snap
    snap.status = health.get("status") or "unreachable"
    snap.healthy = snap.status == "ok"
    status = scrape(port, "/statusz", timeout=timeout)
    sections = (status or {}).get("sections") or {}
    serving = sections.get("serving")
    if isinstance(serving, dict) and "error" not in serving:
        snap.queue_depth = serving.get("queue_depth")
        ledger = serving.get("ledger") or {}
        snap.in_flight = ledger.get("in_flight")
        snap.completed = ledger.get("completed")
        metrics = serving.get("metrics") or {}
        snap.tokens_per_sec = metrics.get("tokens_per_sec")
        snap.tokens_out = metrics.get("tokens_out")
        slo = metrics.get("slo")
        if isinstance(slo, dict):
            snap.slo = slo
        pool = serving.get("page_pool") or {}
        snap.occupancy = pool.get("mem_occupancy") or pool.get("occupancy")
        snap.active_rows = pool.get("active_rows")
    prefix = sections.get("prefix_cache")
    if isinstance(prefix, dict) and "error" not in prefix:
        snap.prefix_stats = {
            k: prefix.get(k)
            for k in ("entries", "hits", "misses", "evictions", "hit_rate")
        }
        snap.prefix_digests = frozenset(
            prefix.get("resident_digests") or ()
        )
    return snap


def fleet_slo_rollup(
    snapshots: dict[int, ReplicaSnapshot],
) -> dict[str, dict]:
    """Fold per-replica SLO burn snapshots into one fleet-wide view per
    tier. Rates are **count-weighted** (a replica that served 10× the
    requests moves the fleet rate 10× as much — an unweighted mean would
    let an idle replica's clean 0.0 mask a busy replica's burn); the
    EWMA column takes the fleet max, because burn alerts care about the
    worst replica, not the average one."""
    out: dict[str, dict] = {}
    for snap in snapshots.values():
        for tier, s in (snap.slo or {}).items():
            if not isinstance(s, dict):
                continue
            agg = out.setdefault(tier, {
                "window_count": 0, "window_missed": 0,
                "total": 0, "missed": 0, "max_ewma": 0.0,
                "replicas": 0,
            })
            agg["window_count"] += int(s.get("window_count") or 0)
            agg["window_missed"] += int(s.get("window_missed") or 0)
            agg["total"] += int(s.get("total") or 0)
            agg["missed"] += int(s.get("missed") or 0)
            agg["max_ewma"] = max(
                agg["max_ewma"], float(s.get("ewma") or 0.0)
            )
            agg["replicas"] += 1
    for agg in out.values():
        n = agg["window_count"]
        agg["window_rate"] = (
            round(agg["window_missed"] / n, 6) if n else 0.0
        )
        agg["max_ewma"] = round(agg["max_ewma"], 6)
    return dict(sorted(out.items()))


class ScrapeLoop:
    """Background scrape plane over a sidecar directory.

    Re-discovers ``fleet_rank<k>.json`` sidecars and snapshots every
    replica each ``interval``; :meth:`snapshots` hands the router a
    consistent copy. A replica that fails to answer keeps its previous
    load fields (stale beats blank) but flips unhealthy after
    ``unreachable_after`` consecutive failures — one lost scrape on a
    busy host must not drain a healthy replica.
    """

    def __init__(
        self,
        directory: str,
        *,
        interval: float = 0.5,
        timeout: float = 2.0,
        unreachable_after: int = 2,
        on_snapshot=None,
    ):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.directory = directory
        self.interval = interval
        self.timeout = timeout
        self.unreachable_after = max(1, int(unreachable_after))
        self.on_snapshot = on_snapshot
        # Extra observers (autoscaler, tests) ride the same tick as the
        # router's on_snapshot callback; each is isolated — one raising
        # observer must not starve the others or kill the plane.
        self._observers: list = []
        self._lock = threading.Lock()
        self._snapshots: dict[int, ReplicaSnapshot] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ScrapeLoop":
        if self._thread is not None:
            raise RuntimeError("scrape loop already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-scrape", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def __enter__(self) -> "ScrapeLoop":
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the loop ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval)

    def tick(self) -> dict[int, ReplicaSnapshot]:
        """One full discovery + scrape pass (also callable inline — the
        tests and the router's synchronous warm-up use it directly)."""
        sidecars = find_fleet_sidecars(self.directory)
        fresh: dict[int, ReplicaSnapshot] = {}
        for rank, side in sidecars.items():
            snap = snapshot_replica(
                rank, int(side["port"]), timeout=self.timeout, retries=1
            )
            with self._lock:
                prev = self._snapshots.get(rank)
            if snap.status == "unreachable" and prev is not None:
                snap.consecutive_failures = prev.consecutive_failures + 1
                if snap.consecutive_failures < self.unreachable_after:
                    # Grace window: keep last-known state (still
                    # unhealthy for *new* dispatch only once the window
                    # closes — see healthy flip below).
                    snap.status = prev.status
                    snap.healthy = prev.healthy
                snap.queue_depth = prev.queue_depth
                snap.in_flight = prev.in_flight
                snap.tokens_per_sec = prev.tokens_per_sec
                snap.tokens_out = prev.tokens_out
                snap.completed = prev.completed
                snap.occupancy = prev.occupancy
                snap.slo = prev.slo
                snap.prefix_digests = prev.prefix_digests
                snap.prefix_stats = prev.prefix_stats
            fresh[rank] = snap
        with self._lock:
            self._snapshots = fresh
            self.ticks += 1
            observers = list(self._observers)
        for obs in ([self.on_snapshot] if self.on_snapshot else []) + observers:
            try:
                obs(dict(fresh))
            except Exception:
                pass  # observer must never kill the plane
        return fresh

    def add_observer(self, fn) -> None:
        """Register an extra per-tick observer (called with a copy of the
        fresh snapshot map, after ``on_snapshot``)."""
        with self._lock:
            self._observers.append(fn)

    # -- consumers -----------------------------------------------------------
    def snapshots(self) -> dict[int, ReplicaSnapshot]:
        with self._lock:
            return dict(self._snapshots)

    def wait_for_replicas(self, n: int, timeout: float = 60.0) -> bool:
        """Block until ``n`` replicas scrape healthy (fleet start-up
        barrier). Ticks inline so callers don't race the interval."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            healthy = [
                s for s in self.tick().values() if s.healthy
            ]
            if len(healthy) >= n:
                return True
            time.sleep(min(self.interval, 0.2))
        return False

    def rows(self) -> list[dict]:
        """Status rows in the ``tools/gang_status.py`` table shape —
        feeds ``telemetry.aggregate.render_status_markdown`` and the
        bench's per-replica skew report."""
        out = []
        for rank, s in sorted(self.snapshots().items()):
            out.append({
                "rank": rank,
                "port": s.port,
                "status": s.status,
                "queue_depth": s.queue_depth,
                "in_flight": s.in_flight,
                "tokens_per_sec": s.tokens_per_sec,
                "occupancy": s.occupancy,
                "prefix_entries": s.prefix_stats.get("entries"),
                "prefix_hit_rate": s.prefix_stats.get("hit_rate"),
                "slo": {
                    tier: (v or {}).get("ewma")
                    for tier, v in sorted((s.slo or {}).items())
                },
            })
        return out
