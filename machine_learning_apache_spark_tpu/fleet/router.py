"""Fleet router — health-aware, affinity-first dispatch over N replicas.

The decision core, :func:`pick_replica`, is a pure function over
``{rank: ReplicaSnapshot}`` so every policy is unit-testable on
synthetic snapshots, no sockets involved:

- ``round_robin`` — cycle the healthy set (the baseline the affinity
  gate in ``tools/fleet_bench.py`` measures against);
- ``least_loaded`` — min in-flight over healthy replicas;
- ``affinity`` (default) — prefer healthy replicas the
  :class:`~machine_learning_apache_spark_tpu.fleet.affinity.AffinityTable`
  says already hold the prompt's prefix (least-loaded among them),
  falling back to least-loaded overall.

:class:`FleetRouter` wraps the decision in the full dispatch loop:
admission (SLO tiers + tenant quotas) → pick → POST → and *drain-around*
on refusals. The retry taxonomy is the whole fault story:

- **connection refused / 503** — the request never entered that
  replica's queue; safe to retry on the next-best replica, and the
  refusing rank goes into a penalty box until a scrape sees ``/healthz``
  recover.
- **429** — the replica queue pushed back; try the others, and if every
  replica pushes back, surface one ``FleetBackpressure`` with the max
  retry-after (the fleet really is full).
- **connection lost mid-request / 5xx** — the request may have been
  decoding; it is *not* silently retried (that is the "only the killed
  replica's in-flight is lost" conservation story) and counts failed.
- **504** — the deadline expired inside the replica; terminal as
  ``expired`` (the engine already booked the same outcome).

**Straggler hedging** (Dean & Barroso, "The Tail at Scale"; off by
default, ``MLSPARK_FLEET_HEDGE``): when a dispatch on an eligible tier
is still outstanding after the hedge delay (a multiple of the admission
layer's service-time EWMA), the router issues ONE duplicate to a second
healthy replica — never the same rank. First response wins; the loser
is reaped through ``POST /v1/cancel``, keyed by the router-minted trace
id both attempts shared. A hedge is only ever issued while the primary
is still *in flight* — a terminal lost/5xx never spawns a new attempt
(lost-is-lost holds), though an already-in-flight hedge may still save
the request. ``hedged`` and ``cancelled`` are attempt-level side
counters, deliberately outside the conservation law: a hedged request
still lands in exactly one terminal bucket.

Every terminal outcome lands in the router ledger, which obeys the same
conservation law as the engine's: submitted == completed + rejected +
unavailable + failed + expired. ``check_conservation`` raises otherwise.
"""

from __future__ import annotations

import itertools
import json
import queue as _pyqueue
import threading
import time
import urllib.error
import urllib.request

from machine_learning_apache_spark_tpu.fleet.admission import (
    FleetAdmission,
    FleetBackpressure,
)
from machine_learning_apache_spark_tpu.fleet.affinity import AffinityTable
from machine_learning_apache_spark_tpu.fleet.scrape import (
    ReplicaSnapshot,
    ScrapeLoop,
    fleet_slo_rollup,
)
from machine_learning_apache_spark_tpu.serving.metrics import BurnRate
from machine_learning_apache_spark_tpu.serving.queue import DeadlineExceeded
from machine_learning_apache_spark_tpu.telemetry import events as _events
from machine_learning_apache_spark_tpu.telemetry import (
    registry as _registry,
)
from machine_learning_apache_spark_tpu.telemetry import spans as _spans
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as _tracectx,
)
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

POLICIES = ("affinity", "least_loaded", "round_robin")

#: Affinity is load-bounded: a warm (prefix-resident) replica is
#: preferred only while its scraped load is within this many requests of
#: the least-loaded healthy replica. Unbounded affinity pins traffic:
#: after a failover every digest's routing memory points at the
#: survivor, and a restarted replica would never see a request again —
#: cache residency must lose to a big enough load gap.
AFFINITY_LOAD_SLACK = 2.0


class FleetUnavailable(RuntimeError):
    """No healthy replica could take the request."""


class FleetRequestFailed(RuntimeError):
    """The request was dispatched and lost (replica died mid-decode) or
    the decode itself failed — not retried, by design."""

    def __init__(self, msg: str, *, rank: int | None = None,
                 status: int | None = None):
        super().__init__(msg)
        self.rank = rank
        self.status = status


def pick_replica(
    snapshots: dict[int, ReplicaSnapshot],
    *,
    policy: str = "affinity",
    candidates: set[int] | None = None,
    exclude: set[int] | None = None,
    rr_state: itertools.count | None = None,
) -> int | None:
    """The dispatch decision, pure over snapshots. ``candidates`` is the
    affinity table's claim for this prompt; ``exclude`` is ranks already
    tried this request. Unhealthy replicas are never picked — that *is*
    the 503-draining property. Returns a rank or None."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (pick from {POLICIES})")
    exclude = exclude or set()
    healthy = sorted(
        r for r, s in snapshots.items() if s.healthy and r not in exclude
    )
    if not healthy:
        return None
    if policy == "round_robin":
        i = next(rr_state) if rr_state is not None else 0
        return healthy[i % len(healthy)]
    coldest = min(healthy, key=lambda r: (snapshots[r].load, r))
    if policy == "affinity" and candidates:
        warm = [r for r in healthy if r in candidates]
        if warm:
            best = min(warm, key=lambda r: (snapshots[r].load, r))
            if snapshots[best].load <= (
                snapshots[coldest].load + AFFINITY_LOAD_SLACK
            ):
                return best
    return coldest


class ReplicaClient:
    """Blocking HTTP client for one dispatch attempt. Separates
    connection-establishment failures (safe to retry elsewhere) from
    mid-request losses (not safe — the work may be half done)."""

    @staticmethod
    def generate(
        port: int,
        text: str,
        *,
        deadline_s: float | None,
        tier: str,
        tenant: str | None,
        timeout: float,
        traceparent: str | None = None,
    ) -> tuple[str, int | None, dict]:
        """Returns ``(kind, http_status, payload)`` with kind in
        {"ok", "refused", "backpressure", "failed", "lost", "expired"}.
        ``traceparent`` (when tracing is on and the request was sampled)
        rides as the W3C header so the replica joins the trace."""
        body = json.dumps({
            "text": text,
            "deadline_s": deadline_s,
            "tier": tier,
            "tenant": tenant,
        }).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if traceparent is not None:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=body,
            headers=headers,
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return "ok", resp.status, json.loads(
                    resp.read().decode("utf-8")
                )
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:
                payload = {}
            if e.code == 429:
                return "backpressure", 429, payload
            if e.code == 503:
                return "refused", 503, payload
            if e.code == 504:
                # The deadline expired inside the replica — the engine
                # booked ``expired``; mirror the taxonomy, still terminal.
                return "expired", 504, payload
            # 400/500: the replica answered — the request itself is
            # terminal there; retrying would double-spend decode work.
            return "failed", e.code, payload
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), ConnectionRefusedError):
                # Never reached a socket: replica dead or restarting.
                return "refused", None, {"error": repr(e)}
            return "lost", None, {"error": repr(e)}
        except Exception as e:  # noqa: BLE001 — socket reset mid-read etc.
            return "lost", None, {"error": repr(e)}

    @staticmethod
    def cancel(port: int, trace_id: str, *, timeout: float = 5.0) -> bool:
        """Best-effort loser reap after a hedge race: ``POST /v1/cancel``
        keyed by the router-minted trace id. False on any failure — a
        cancel that misses only wastes the loser's remaining decode."""
        body = json.dumps({"trace_id": trace_id}).encode("utf-8")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/cancel",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
            return bool(payload.get("cancelled"))
        except Exception:  # noqa: BLE001 — best-effort by contract
            return False


class FleetRouter:
    """N replicas, one front door.

    ``key_fn(text) -> digest`` supplies the prefix-affinity key (wire it
    to ``serving.prefix_digest`` over the same tokenizer the replicas
    run — see ``tools/fleet_bench.py``); None disables affinity for that
    request. ``snapshot_source`` defaults to a background
    :class:`ScrapeLoop` over ``directory`` but tests inject a plain
    callable returning synthetic snapshots."""

    def __init__(
        self,
        directory: str | None = None,
        *,
        policy: str | None = None,
        key_fn=None,
        admission: FleetAdmission | None = None,
        affinity: AffinityTable | None = None,
        snapshot_source=None,
        scrape_interval: float | None = None,
        request_timeout_s: float = 120.0,
        clock=time.monotonic,
        hedge: bool | None = None,
        hedge_tiers=None,
        hedge_delay_factor: float | None = None,
        hedge_min_delay_s: float | None = None,
    ):
        from machine_learning_apache_spark_tpu.utils import env as envcfg

        if policy is None:
            policy = envcfg.get_str("MLSPARK_FLEET_POLICY")
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} (pick from {POLICIES}; check "
                "MLSPARK_FLEET_POLICY)"
            )
        if snapshot_source is None and directory is None:
            raise ValueError(
                "pass a sidecar directory (scrape-loop mode) or an "
                "explicit snapshot_source"
            )
        if scrape_interval is None:
            scrape_interval = envcfg.get_float(
                "MLSPARK_FLEET_SCRAPE_INTERVAL"
            )
        self.policy = policy
        self.key_fn = key_fn
        self.clock = clock
        self.request_timeout_s = request_timeout_s
        # Straggler hedging (arg > env > default; off by default so the
        # plain dispatch path is byte-for-byte what it always was).
        if hedge is None:
            hedge = envcfg.get_bool("MLSPARK_FLEET_HEDGE")
        if hedge_tiers is None:
            hedge_tiers = envcfg.get_str("MLSPARK_FLEET_HEDGE_TIERS")
        if isinstance(hedge_tiers, str):
            hedge_tiers = tuple(
                t.strip() for t in hedge_tiers.split(",") if t.strip()
            )
        if hedge_delay_factor is None:
            hedge_delay_factor = envcfg.get_float(
                "MLSPARK_FLEET_HEDGE_DELAY_FACTOR"
            )
        if hedge_min_delay_s is None:
            hedge_min_delay_s = envcfg.get_float(
                "MLSPARK_FLEET_HEDGE_MIN_DELAY_S"
            )
        self.hedge = bool(hedge)
        self.hedge_tiers = tuple(hedge_tiers)
        self.hedge_delay_factor = float(hedge_delay_factor)
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self.admission = admission or FleetAdmission()
        self.affinity = affinity or AffinityTable()
        self._scrape: ScrapeLoop | None = None
        if snapshot_source is None:
            self._scrape = ScrapeLoop(
                directory,
                interval=scrape_interval,
                on_snapshot=self._on_scrape,
            )
            snapshot_source = self._scrape.snapshots
        self._snapshot_source = snapshot_source
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # Penalty box: rank -> monotonic time of last refusal. A boxed
        # rank is skipped until a scrape reports it healthy again (the
        # scrape loop is the source of recovery truth).
        self._down: dict[int, float] = {}
        # Ranks present in the last scrape — the diff against each fresh
        # tick identifies vanished ranks whose routing state must purge.
        self._seen_ranks: set[int] = set()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0      # fleet admission / all-replica backpressure
        self.unavailable = 0   # no healthy replica reachable
        self.failed = 0        # dispatched and lost / decode failure
        self.expired = 0       # deadline burned down (locally or 504)
        self.retries = 0
        # Attempt-level hedging counters, outside the conservation law:
        # a hedged request still retires in exactly one terminal bucket.
        self.hedged = 0        # duplicate dispatches issued
        self.cancelled = 0     # loser reaps sent via /v1/cancel
        self._per_replica: dict[int, dict] = {}
        # Per-tier SLO burn gauges over *routed* outcomes: a request
        # "missed" unless it completed within its deadline — rejected,
        # unavailable, and failed dispatches all burn budget, because the
        # client's SLO does not care which layer dropped the ball.
        self._burn: dict[str, BurnRate] = {}
        self._reg = _registry.get_registry()
        self._counters = {
            name: self._reg.counter("fleet", name)
            for name in ("submitted", "completed", "rejected",
                         "unavailable", "failed", "expired", "retries",
                         "hedged", "cancelled")
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._scrape is not None:
            self._scrape.start()
        return self

    def stop(self) -> None:
        if self._scrape is not None:
            self._scrape.stop()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def wait_for_replicas(self, n: int, timeout: float = 120.0) -> bool:
        if self._scrape is None:
            return len([
                s for s in self._snapshot_source().values() if s.healthy
            ]) >= n
        return self._scrape.wait_for_replicas(n, timeout=timeout)

    # -- scrape feedback -----------------------------------------------------
    def _on_scrape(self, snapshots: dict[int, ReplicaSnapshot]) -> None:
        """Scrape tick: refresh affinity residency, let recovered
        replicas out of the penalty box, and purge *all* routing state
        for ranks that vanished from discovery — a retired rank's stale
        penalty-box or affinity entry must not shadow a future rank
        reusing the slot."""
        with self._lock:
            for rank, snap in snapshots.items():
                if snap.healthy:
                    self._down.pop(rank, None)
            gone = [r for r in self._down if r not in snapshots]
            for r in gone:
                self._down.pop(r, None)
            vanished = [
                r for r in self._seen_ranks if r not in snapshots
            ]
            self._seen_ranks = set(snapshots)
        for r in vanished:
            self.affinity.forget_rank(r)
        for rank, snap in snapshots.items():
            if snap.healthy:
                self.affinity.observe_scrape(rank, snap.prefix_digests)
            else:
                self.affinity.forget_rank(rank)

    def _usable_snapshots(self) -> dict[int, ReplicaSnapshot]:
        snaps = self._snapshot_source()
        with self._lock:
            down = set(self._down)
        return {r: s for r, s in snaps.items() if r not in down}

    def _box(self, rank: int) -> None:
        with self._lock:
            self._down[rank] = self.clock()

    # -- the dispatch loop ---------------------------------------------------
    def submit(
        self,
        text: str,
        *,
        tier: str = "interactive",
        tenant: str | None = None,
        deadline_s: float | None = None,
    ) -> dict:
        """Route one request to completion. Returns the replica's 200
        payload. Raises :class:`FleetBackpressure` (whole fleet at
        capacity / quota exhausted), :class:`FleetUnavailable` (no
        healthy replica), :class:`FleetRequestFailed` (dispatched and
        lost or decode-failed — the non-retried taxonomy), or
        :class:`~machine_learning_apache_spark_tpu.serving.queue.
        DeadlineExceeded` (budget burned down before dispatch, or the
        replica 504'd — outcome ``expired`` either way).

        Distributed tracing: the router is where a request's trace is
        **minted** (head-sampled once, here). The whole dispatch lives
        under a ``fleet.submit`` span; each dispatch attempt gets a
        ``fleet.attempt`` child span and a fresh child span id sent as
        the ``traceparent`` header — so a 503-drained attempt and its
        successful retry land as siblings under one trace, each joined
        to its replica-side spans by a distinct cross-process edge."""
        t0 = self.clock()
        self._bump("submitted")
        try:
            lease = self.admission.admit(tier=tier, tenant=tenant)
        except FleetBackpressure:
            self._bump("rejected")
            raise
        ctx = _tracectx.mint()
        digest = None
        retries = 0
        outcome, out_rank, status = "failed", None, None
        deadline = deadline_s if deadline_s is not None else lease.deadline_s
        with _tracectx.use(ctx), _spans.span("fleet.submit", tier=tier):
            try:
                if self.key_fn is not None:
                    try:
                        digest = self.key_fn(text)
                    except Exception:
                        digest = None
                tried: set[int] = set()
                backpressure: FleetBackpressure | None = None
                while True:
                    # Pre-dispatch deadline check: a request that burned
                    # its whole budget cycling the retry/penalty-box loop
                    # fails HERE as expired — dispatching with a negative
                    # remaining budget would only make a replica decode
                    # tokens nobody is still waiting for.
                    remaining = deadline - (self.clock() - t0)
                    if remaining <= 0:
                        outcome = "expired"
                        self._bump("expired")
                        raise DeadlineExceeded(
                            f"deadline of {deadline:.3f}s elapsed before "
                            f"dispatch (retries={retries})"
                        )
                    snaps = self._usable_snapshots()
                    rank = pick_replica(
                        snaps,
                        policy=self.policy,
                        candidates=self.affinity.candidates(digest),
                        exclude=tried,
                        rr_state=self._rr,
                    )
                    if rank is None:
                        if backpressure is not None:
                            outcome = "rejected"
                            self._bump("rejected")
                            raise backpressure
                        outcome = "unavailable"
                        self._bump("unavailable")
                        raise FleetUnavailable(
                            f"no healthy replica (tried {sorted(tried)})"
                        )
                    tried.add(rank)
                    snap = snaps[rank]
                    if self.hedge and tier in self.hedge_tiers:
                        rank, kind, status, payload = self._dispatch_hedged(
                            snaps, rank, snap, text, remaining=remaining,
                            tier=tier, tenant=tenant, ctx=ctx,
                            digest=digest, tried=tried,
                        )
                    else:
                        rank, kind, status, payload = self._attempt(
                            rank, snap.port, text, budget=remaining,
                            tier=tier, tenant=tenant, ctx=ctx,
                        )
                    if kind == "ok":
                        self.affinity.note_routed(digest, rank)
                        self._note(rank, "completed")
                        outcome, out_rank = "completed", rank
                        self._bump("completed")
                        return payload
                    if kind == "refused":
                        # 503 / connection refused: never entered the
                        # queue. Box the rank (scrape recovery lets it
                        # back) and drain to the next-best replica.
                        self._box(rank)
                        self.affinity.forget_rank(rank)
                        self._note(rank, "refused")
                        retries += 1
                        self._bump("retries")
                        continue
                    if kind == "backpressure":
                        self._note(rank, "backpressure")
                        ra = (payload or {}).get("retry_after") or 0.05
                        if backpressure is None or ra > backpressure.retry_after:
                            backpressure = FleetBackpressure(
                                (payload or {}).get("depth", 0), ra,
                                scope=f"replica:{rank}",
                            )
                        retries += 1
                        self._bump("retries")
                        continue
                    if kind == "expired":
                        # The replica's engine reaped the request at its
                        # deadline (504): terminal, same outcome bucket
                        # as the local pre-dispatch expiry.
                        self._note(rank, "expired")
                        outcome, out_rank = "expired", rank
                        self._bump("expired")
                        raise DeadlineExceeded(
                            f"request expired on replica {rank}: "
                            f"{(payload or {}).get('error')}"
                        )
                    # "lost" or "failed": terminal, not retried.
                    self._note(rank, "lost" if kind == "lost" else "failed")
                    outcome, out_rank = kind, rank
                    self._bump("failed")
                    if kind == "lost":
                        # The socket died under a dispatched request —
                        # treat the rank as down for new traffic too.
                        self._box(rank)
                    raise FleetRequestFailed(
                        f"request {kind} on replica {rank} "
                        f"(status={status}): {(payload or {}).get('error')}",
                        rank=rank, status=status,
                    )
            finally:
                total = self.clock() - t0
                self.admission.release(lease, service_s=total)
                self._observe_slo(
                    tier, outcome != "completed" or total > deadline
                )
                _events.annotate(
                    "fleet.request",
                    outcome=outcome, replica=out_rank, tier=tier,
                    tenant=tenant, retries=retries, total_s=round(total, 6),
                    status=status,
                )

    # -- dispatch attempts ---------------------------------------------------
    def _attempt(
        self, rank: int, port: int, text: str, *, budget: float,
        tier: str, tenant: str | None, ctx,
    ) -> tuple[int, str, int | None, dict]:
        """One wire dispatch under its own ``fleet.attempt`` span.
        ``budget`` is the request's *remaining* deadline — what the
        replica gets as ``deadline_s``, so a late retry or a hedge is
        granted only the time actually left. Runs on the submit thread
        (plain path) or a hedge worker thread (the ``use(ctx)`` wrap is
        what keeps the worker's events on the request's trace)."""
        self._note(rank, "dispatched")
        # One child span id per attempt: the replica records it as
        # remote_parent, which is how the merged view attaches each
        # replica's spans to the right attempt.
        attempt = _tracectx.child(ctx)
        attempt_attrs = {"replica": rank}
        if attempt is not None:
            attempt_attrs["ctx_span"] = attempt.span_id
        with _tracectx.use(ctx), _spans.span("fleet.attempt",
                                             **attempt_attrs):
            kind, status, payload = ReplicaClient.generate(
                port, text,
                deadline_s=budget, tier=tier, tenant=tenant,
                timeout=min(self.request_timeout_s, budget + 30.0),
                traceparent=(
                    None if attempt is None
                    else _tracectx.to_traceparent(attempt)
                ),
            )
        return rank, kind, status, payload

    def _dispatch_hedged(
        self, snaps, rank: int, snap, text: str, *, remaining: float,
        tier: str, tenant: str | None, ctx, digest, tried: set[int],
    ) -> tuple[int, str, int | None, dict]:
        """One dispatch round with straggler hedging: launch the primary,
        and if it is still outstanding after the hedge delay, launch ONE
        duplicate on a different healthy rank. First ``ok`` wins and the
        loser is reaped via ``/v1/cancel``; with no winner the two
        outcomes reduce to a single result for the caller's taxonomy
        (terminal > backpressure > refused — a terminal sibling must
        dominate, or the retry loop would replay half-done work)."""
        t_call = self.clock()
        results: _pyqueue.Queue = _pyqueue.Queue()
        outstanding: dict[int, int] = {}  # rank -> port

        def run(a_rank: int, a_port: int, budget: float) -> None:
            try:
                results.put(self._attempt(
                    a_rank, a_port, text, budget=budget,
                    tier=tier, tenant=tenant, ctx=ctx,
                ))
            except Exception as e:  # noqa: BLE001 — an attempt must report
                results.put((a_rank, "lost", None, {"error": repr(e)}))

        def spawn(a_rank: int, a_port: int, budget: float) -> None:
            outstanding[a_rank] = a_port
            threading.Thread(
                target=run, args=(a_rank, a_port, budget),
                name=f"fleet-hedge-{a_rank}", daemon=True,
            ).start()

        spawn(rank, snap.port, remaining)
        delay = max(
            self.hedge_min_delay_s,
            self.hedge_delay_factor * self.admission.service_ewma(),
        )
        try:
            res = results.get(timeout=min(delay, max(remaining, 0.01)))
            # Primary answered inside the hedge delay: no hedge, and the
            # result (of whatever kind) follows the plain taxonomy.
            outstanding.pop(res[0], None)
            return res
        except _pyqueue.Empty:
            pass
        # Primary still out past the delay: presume straggler, hedge
        # once. Never the same rank (exclude everything tried); a hedge
        # is issued only while the primary is in flight — a terminal
        # result never spawns one, so lost-is-lost survives.
        h_rank = pick_replica(
            snaps,
            policy=self.policy,
            candidates=self.affinity.candidates(digest),
            exclude=set(tried) | set(outstanding),
            rr_state=self._rr,
        )
        if h_rank is not None:
            tried.add(h_rank)
            self._bump("hedged")
            self._note(h_rank, "hedged")
            _events.annotate(
                "fleet.hedge", primary=rank, hedge=h_rank, tier=tier,
                delay_s=round(delay, 4),
            )
            spawn(
                h_rank, snaps[h_rank].port,
                max(remaining - (self.clock() - t_call), 0.01),
            )
        collected: list[tuple[int, str, int | None, dict]] = []
        while outstanding:
            wait_s = max(
                remaining - (self.clock() - t_call), 0.0
            ) + 35.0  # outlast every attempt's own socket timeout
            try:
                res = results.get(timeout=wait_s)
            except _pyqueue.Empty:
                # Unreachable in practice (attempts time out first);
                # declare the stragglers lost rather than hang forever.
                for d_rank in list(outstanding):
                    outstanding.pop(d_rank)
                    collected.append((
                        d_rank, "lost", None,
                        {"error": "hedge wait timed out"},
                    ))
                break
            outstanding.pop(res[0], None)
            if res[1] == "ok":
                # First response wins. Reap the still-running loser, and
                # book any already-arrived non-ok sibling so the
                # per-replica taxonomy stays truthful.
                for l_rank, l_port in outstanding.items():
                    self._cancel_loser(l_rank, l_port, ctx)
                for c in collected:
                    self._absorb_hedge_result(c)
                return res
            collected.append(res)
        severity = {
            "lost": 0, "failed": 0, "expired": 0,
            "backpressure": 1, "refused": 2,
        }
        collected.sort(key=lambda c: severity.get(c[1], 0))
        head, rest = collected[0], collected[1:]
        for c in rest:
            self._absorb_hedge_result(c)
        return head

    def _absorb_hedge_result(
        self, res: tuple[int, str, int | None, dict]
    ) -> None:
        """Book a hedge sibling's non-winning, non-returned outcome:
        per-replica taxonomy and penalty-box effects still apply, but it
        contributes no request-level terminal bucket — that is its
        sibling's job, and the conservation law demands exactly one."""
        r_rank, kind, _status, _payload = res
        if kind == "refused":
            self._box(r_rank)
            self.affinity.forget_rank(r_rank)
            self._note(r_rank, "refused")
        elif kind == "backpressure":
            self._note(r_rank, "backpressure")
        elif kind == "lost":
            self._box(r_rank)
            self._note(r_rank, "lost")
        elif kind in ("failed", "expired"):
            self._note(r_rank, kind)

    def _cancel_loser(self, rank: int, port: int, ctx) -> None:
        """The race is decided: reap the outstanding duplicate so it
        stops burning pages and launch slots. Fire-and-forget on a
        helper thread — the winner's response must not wait on the
        loser's socket. With tracing off there is no shared trace-id
        key, so the loser simply runs out its own clock (correctness is
        unaffected; only the dead-work savings are forfeited)."""
        if ctx is None:
            return
        self._note(rank, "cancelled")
        self._bump("cancelled")
        threading.Thread(
            target=ReplicaClient.cancel, args=(port, ctx.trace_id),
            name=f"fleet-cancel-{rank}", daemon=True,
        ).start()

    # -- accounting ----------------------------------------------------------
    def _observe_slo(self, tier: str, missed: bool) -> None:
        """Fold one request outcome into the router-side burn gauge for
        its tier. Router semantics are stricter than the replica's: a
        request burns budget unless it **completed within deadline** —
        rejections, unavailability, and failed dispatches all count,
        because the client experienced a miss either way."""
        tier = tier or "interactive"
        with self._lock:
            burn = self._burn.get(tier)
            if burn is None:
                burn = self._burn[tier] = BurnRate()
        burn.observe(missed)
        _registry.get_registry().gauge(
            "fleet", f"slo_burn_{tier}"
        ).set(burn.ewma)

    def _bump(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        self._counters[name].inc()

    def _note(self, rank: int, event: str) -> None:
        with self._lock:
            row = self._per_replica.setdefault(rank, {
                "dispatched": 0, "completed": 0, "refused": 0,
                "backpressure": 0, "failed": 0, "lost": 0,
                "expired": 0, "hedged": 0, "cancelled": 0,
            })
            row[event] = row.get(event, 0) + 1

    def ledger(self) -> dict:
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "unavailable": self.unavailable,
                "failed": self.failed,
                "expired": self.expired,
                # Attempt-level hedge taxonomy — informational, outside
                # the conservation sum (a hedged request still lands in
                # exactly one terminal bucket above).
                "hedged": self.hedged,
                "cancelled": self.cancelled,
            }
        out["in_flight"] = (
            out["submitted"] - out["completed"] - out["rejected"]
            - out["unavailable"] - out["failed"] - out["expired"]
        )
        return out

    def check_conservation(self, *, in_flight: int = 0) -> dict:
        """Router-side conservation law — every submitted request is
        accounted for in exactly one terminal counter."""
        ledger = self.ledger()
        if ledger["in_flight"] != in_flight:
            raise AssertionError(
                f"fleet conservation violated: expected in_flight="
                f"{in_flight}, ledger says {ledger}"
            )
        return ledger

    def stats(self) -> dict:
        with self._lock:
            per_replica = {r: dict(v) for r, v in self._per_replica.items()}
            down = sorted(self._down)
            slo = {tier: b.snapshot() for tier, b in sorted(self._burn.items())}
        return {
            "policy": self.policy,
            "ledger": self.ledger(),
            "retries": self.retries,
            "per_replica": per_replica,
            "down": down,
            # Router-observed burn (every routed outcome) next to the
            # scrape-side rollup of what each replica's engine saw —
            # disagreement between the two is itself a signal (e.g. the
            # router burning on "unavailable" while replicas look clean).
            "slo": slo,
            "slo_fleet": fleet_slo_rollup(self._snapshot_source()),
            "admission": self.admission.stats(),
            "affinity": self.affinity.stats(),
        }
