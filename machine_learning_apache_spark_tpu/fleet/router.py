"""Fleet router — health-aware, affinity-first dispatch over N replicas.

The decision core, :func:`pick_replica`, is a pure function over
``{rank: ReplicaSnapshot}`` so every policy is unit-testable on
synthetic snapshots, no sockets involved:

- ``round_robin`` — cycle the healthy set (the baseline the affinity
  gate in ``tools/fleet_bench.py`` measures against);
- ``least_loaded`` — min in-flight over healthy replicas;
- ``affinity`` (default) — prefer healthy replicas the
  :class:`~machine_learning_apache_spark_tpu.fleet.affinity.AffinityTable`
  says already hold the prompt's prefix (least-loaded among them),
  falling back to least-loaded overall.

:class:`FleetRouter` wraps the decision in the full dispatch loop:
admission (SLO tiers + tenant quotas) → pick → POST → and *drain-around*
on refusals. The retry taxonomy is the whole fault story:

- **connection refused / 503** — the request never entered that
  replica's queue; safe to retry on the next-best replica, and the
  refusing rank goes into a penalty box until a scrape sees ``/healthz``
  recover.
- **429** — the replica queue pushed back; try the others, and if every
  replica pushes back, surface one ``FleetBackpressure`` with the max
  retry-after (the fleet really is full).
- **connection lost mid-request / 5xx** — the request may have been
  decoding; it is *not* silently retried (that is the "only the killed
  replica's in-flight is lost" conservation story) and counts failed.

Every terminal outcome lands in the router ledger, which obeys the same
conservation law as the engine's: submitted == completed + rejected +
unavailable + failed. ``check_conservation`` raises otherwise.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.error
import urllib.request

from machine_learning_apache_spark_tpu.fleet.admission import (
    FleetAdmission,
    FleetBackpressure,
)
from machine_learning_apache_spark_tpu.fleet.affinity import AffinityTable
from machine_learning_apache_spark_tpu.fleet.scrape import (
    ReplicaSnapshot,
    ScrapeLoop,
    fleet_slo_rollup,
)
from machine_learning_apache_spark_tpu.serving.metrics import BurnRate
from machine_learning_apache_spark_tpu.telemetry import events as _events
from machine_learning_apache_spark_tpu.telemetry import (
    registry as _registry,
)
from machine_learning_apache_spark_tpu.telemetry import spans as _spans
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as _tracectx,
)
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

POLICIES = ("affinity", "least_loaded", "round_robin")

#: Affinity is load-bounded: a warm (prefix-resident) replica is
#: preferred only while its scraped load is within this many requests of
#: the least-loaded healthy replica. Unbounded affinity pins traffic:
#: after a failover every digest's routing memory points at the
#: survivor, and a restarted replica would never see a request again —
#: cache residency must lose to a big enough load gap.
AFFINITY_LOAD_SLACK = 2.0


class FleetUnavailable(RuntimeError):
    """No healthy replica could take the request."""


class FleetRequestFailed(RuntimeError):
    """The request was dispatched and lost (replica died mid-decode) or
    the decode itself failed — not retried, by design."""

    def __init__(self, msg: str, *, rank: int | None = None,
                 status: int | None = None):
        super().__init__(msg)
        self.rank = rank
        self.status = status


def pick_replica(
    snapshots: dict[int, ReplicaSnapshot],
    *,
    policy: str = "affinity",
    candidates: set[int] | None = None,
    exclude: set[int] | None = None,
    rr_state: itertools.count | None = None,
) -> int | None:
    """The dispatch decision, pure over snapshots. ``candidates`` is the
    affinity table's claim for this prompt; ``exclude`` is ranks already
    tried this request. Unhealthy replicas are never picked — that *is*
    the 503-draining property. Returns a rank or None."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r} (pick from {POLICIES})")
    exclude = exclude or set()
    healthy = sorted(
        r for r, s in snapshots.items() if s.healthy and r not in exclude
    )
    if not healthy:
        return None
    if policy == "round_robin":
        i = next(rr_state) if rr_state is not None else 0
        return healthy[i % len(healthy)]
    coldest = min(healthy, key=lambda r: (snapshots[r].load, r))
    if policy == "affinity" and candidates:
        warm = [r for r in healthy if r in candidates]
        if warm:
            best = min(warm, key=lambda r: (snapshots[r].load, r))
            if snapshots[best].load <= (
                snapshots[coldest].load + AFFINITY_LOAD_SLACK
            ):
                return best
    return coldest


class ReplicaClient:
    """Blocking HTTP client for one dispatch attempt. Separates
    connection-establishment failures (safe to retry elsewhere) from
    mid-request losses (not safe — the work may be half done)."""

    @staticmethod
    def generate(
        port: int,
        text: str,
        *,
        deadline_s: float | None,
        tier: str,
        tenant: str | None,
        timeout: float,
        traceparent: str | None = None,
    ) -> tuple[str, int | None, dict]:
        """Returns ``(kind, http_status, payload)`` with kind in
        {"ok", "refused", "backpressure", "failed", "lost"}.
        ``traceparent`` (when tracing is on and the request was sampled)
        rides as the W3C header so the replica joins the trace."""
        body = json.dumps({
            "text": text,
            "deadline_s": deadline_s,
            "tier": tier,
            "tenant": tenant,
        }).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if traceparent is not None:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=body,
            headers=headers,
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return "ok", resp.status, json.loads(
                    resp.read().decode("utf-8")
                )
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:
                payload = {}
            if e.code == 429:
                return "backpressure", 429, payload
            if e.code == 503:
                return "refused", 503, payload
            # 400/500/504: the replica answered — the request itself is
            # terminal there; retrying would double-spend decode work.
            return "failed", e.code, payload
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), ConnectionRefusedError):
                # Never reached a socket: replica dead or restarting.
                return "refused", None, {"error": repr(e)}
            return "lost", None, {"error": repr(e)}
        except Exception as e:  # noqa: BLE001 — socket reset mid-read etc.
            return "lost", None, {"error": repr(e)}


class FleetRouter:
    """N replicas, one front door.

    ``key_fn(text) -> digest`` supplies the prefix-affinity key (wire it
    to ``serving.prefix_digest`` over the same tokenizer the replicas
    run — see ``tools/fleet_bench.py``); None disables affinity for that
    request. ``snapshot_source`` defaults to a background
    :class:`ScrapeLoop` over ``directory`` but tests inject a plain
    callable returning synthetic snapshots."""

    def __init__(
        self,
        directory: str | None = None,
        *,
        policy: str | None = None,
        key_fn=None,
        admission: FleetAdmission | None = None,
        affinity: AffinityTable | None = None,
        snapshot_source=None,
        scrape_interval: float | None = None,
        request_timeout_s: float = 120.0,
        clock=time.monotonic,
    ):
        from machine_learning_apache_spark_tpu.utils import env as envcfg

        if policy is None:
            policy = envcfg.get_str("MLSPARK_FLEET_POLICY")
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r} (pick from {POLICIES}; check "
                "MLSPARK_FLEET_POLICY)"
            )
        if snapshot_source is None and directory is None:
            raise ValueError(
                "pass a sidecar directory (scrape-loop mode) or an "
                "explicit snapshot_source"
            )
        if scrape_interval is None:
            scrape_interval = envcfg.get_float(
                "MLSPARK_FLEET_SCRAPE_INTERVAL"
            )
        self.policy = policy
        self.key_fn = key_fn
        self.clock = clock
        self.request_timeout_s = request_timeout_s
        self.admission = admission or FleetAdmission()
        self.affinity = affinity or AffinityTable()
        self._scrape: ScrapeLoop | None = None
        if snapshot_source is None:
            self._scrape = ScrapeLoop(
                directory,
                interval=scrape_interval,
                on_snapshot=self._on_scrape,
            )
            snapshot_source = self._scrape.snapshots
        self._snapshot_source = snapshot_source
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # Penalty box: rank -> monotonic time of last refusal. A boxed
        # rank is skipped until a scrape reports it healthy again (the
        # scrape loop is the source of recovery truth).
        self._down: dict[int, float] = {}
        # Ranks present in the last scrape — the diff against each fresh
        # tick identifies vanished ranks whose routing state must purge.
        self._seen_ranks: set[int] = set()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0      # fleet admission / all-replica backpressure
        self.unavailable = 0   # no healthy replica reachable
        self.failed = 0        # dispatched and lost / decode failure
        self.retries = 0
        self._per_replica: dict[int, dict] = {}
        # Per-tier SLO burn gauges over *routed* outcomes: a request
        # "missed" unless it completed within its deadline — rejected,
        # unavailable, and failed dispatches all burn budget, because the
        # client's SLO does not care which layer dropped the ball.
        self._burn: dict[str, BurnRate] = {}
        self._reg = _registry.get_registry()
        self._counters = {
            name: self._reg.counter("fleet", name)
            for name in ("submitted", "completed", "rejected",
                         "unavailable", "failed", "retries")
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._scrape is not None:
            self._scrape.start()
        return self

    def stop(self) -> None:
        if self._scrape is not None:
            self._scrape.stop()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def wait_for_replicas(self, n: int, timeout: float = 120.0) -> bool:
        if self._scrape is None:
            return len([
                s for s in self._snapshot_source().values() if s.healthy
            ]) >= n
        return self._scrape.wait_for_replicas(n, timeout=timeout)

    # -- scrape feedback -----------------------------------------------------
    def _on_scrape(self, snapshots: dict[int, ReplicaSnapshot]) -> None:
        """Scrape tick: refresh affinity residency, let recovered
        replicas out of the penalty box, and purge *all* routing state
        for ranks that vanished from discovery — a retired rank's stale
        penalty-box or affinity entry must not shadow a future rank
        reusing the slot."""
        with self._lock:
            for rank, snap in snapshots.items():
                if snap.healthy:
                    self._down.pop(rank, None)
            gone = [r for r in self._down if r not in snapshots]
            for r in gone:
                self._down.pop(r, None)
            vanished = [
                r for r in self._seen_ranks if r not in snapshots
            ]
            self._seen_ranks = set(snapshots)
        for r in vanished:
            self.affinity.forget_rank(r)
        for rank, snap in snapshots.items():
            if snap.healthy:
                self.affinity.observe_scrape(rank, snap.prefix_digests)
            else:
                self.affinity.forget_rank(rank)

    def _usable_snapshots(self) -> dict[int, ReplicaSnapshot]:
        snaps = self._snapshot_source()
        with self._lock:
            down = set(self._down)
        return {r: s for r, s in snaps.items() if r not in down}

    def _box(self, rank: int) -> None:
        with self._lock:
            self._down[rank] = self.clock()

    # -- the dispatch loop ---------------------------------------------------
    def submit(
        self,
        text: str,
        *,
        tier: str = "interactive",
        tenant: str | None = None,
        deadline_s: float | None = None,
    ) -> dict:
        """Route one request to completion. Returns the replica's 200
        payload. Raises :class:`FleetBackpressure` (whole fleet at
        capacity / quota exhausted), :class:`FleetUnavailable` (no
        healthy replica), :class:`FleetRequestFailed` (dispatched and
        lost or decode-failed — the non-retried taxonomy).

        Distributed tracing: the router is where a request's trace is
        **minted** (head-sampled once, here). The whole dispatch lives
        under a ``fleet.submit`` span; each dispatch attempt gets a
        ``fleet.attempt`` child span and a fresh child span id sent as
        the ``traceparent`` header — so a 503-drained attempt and its
        successful retry land as siblings under one trace, each joined
        to its replica-side spans by a distinct cross-process edge."""
        t0 = self.clock()
        self._bump("submitted")
        try:
            lease = self.admission.admit(tier=tier, tenant=tenant)
        except FleetBackpressure:
            self._bump("rejected")
            raise
        ctx = _tracectx.mint()
        digest = None
        retries = 0
        outcome, out_rank, status = "failed", None, None
        deadline = deadline_s if deadline_s is not None else lease.deadline_s
        with _tracectx.use(ctx), _spans.span("fleet.submit", tier=tier):
            try:
                if self.key_fn is not None:
                    try:
                        digest = self.key_fn(text)
                    except Exception:
                        digest = None
                tried: set[int] = set()
                backpressure: FleetBackpressure | None = None
                while True:
                    snaps = self._usable_snapshots()
                    rank = pick_replica(
                        snaps,
                        policy=self.policy,
                        candidates=self.affinity.candidates(digest),
                        exclude=tried,
                        rr_state=self._rr,
                    )
                    if rank is None:
                        if backpressure is not None:
                            outcome = "rejected"
                            self._bump("rejected")
                            raise backpressure
                        outcome = "unavailable"
                        self._bump("unavailable")
                        raise FleetUnavailable(
                            f"no healthy replica (tried {sorted(tried)})"
                        )
                    tried.add(rank)
                    snap = snaps[rank]
                    self._note(rank, "dispatched")
                    # One child span id per attempt: the replica records
                    # it as remote_parent, which is how the merged view
                    # attaches each replica's spans to the right attempt.
                    attempt = _tracectx.child(ctx)
                    attempt_attrs = {"replica": rank}
                    if attempt is not None:
                        attempt_attrs["ctx_span"] = attempt.span_id
                    with _spans.span("fleet.attempt", **attempt_attrs):
                        kind, status, payload = ReplicaClient.generate(
                            snap.port, text,
                            deadline_s=deadline, tier=tier, tenant=tenant,
                            timeout=min(self.request_timeout_s,
                                        deadline + 30.0),
                            traceparent=(
                                None if attempt is None
                                else _tracectx.to_traceparent(attempt)
                            ),
                        )
                    if kind == "ok":
                        self.affinity.note_routed(digest, rank)
                        self._note(rank, "completed")
                        outcome, out_rank = "completed", rank
                        self._bump("completed")
                        return payload
                    if kind == "refused":
                        # 503 / connection refused: never entered the
                        # queue. Box the rank (scrape recovery lets it
                        # back) and drain to the next-best replica.
                        self._box(rank)
                        self.affinity.forget_rank(rank)
                        self._note(rank, "refused")
                        retries += 1
                        self._bump("retries")
                        continue
                    if kind == "backpressure":
                        self._note(rank, "backpressure")
                        ra = (payload or {}).get("retry_after") or 0.05
                        if backpressure is None or ra > backpressure.retry_after:
                            backpressure = FleetBackpressure(
                                (payload or {}).get("depth", 0), ra,
                                scope=f"replica:{rank}",
                            )
                        retries += 1
                        self._bump("retries")
                        continue
                    # "lost" or "failed": terminal, not retried.
                    self._note(rank, "lost" if kind == "lost" else "failed")
                    outcome, out_rank = kind, rank
                    self._bump("failed")
                    if kind == "lost":
                        # The socket died under a dispatched request —
                        # treat the rank as down for new traffic too.
                        self._box(rank)
                    raise FleetRequestFailed(
                        f"request {kind} on replica {rank} "
                        f"(status={status}): {(payload or {}).get('error')}",
                        rank=rank, status=status,
                    )
            finally:
                total = self.clock() - t0
                self.admission.release(lease, service_s=total)
                self._observe_slo(
                    tier, outcome != "completed" or total > deadline
                )
                _events.annotate(
                    "fleet.request",
                    outcome=outcome, replica=out_rank, tier=tier,
                    tenant=tenant, retries=retries, total_s=round(total, 6),
                    status=status,
                )

    # -- accounting ----------------------------------------------------------
    def _observe_slo(self, tier: str, missed: bool) -> None:
        """Fold one request outcome into the router-side burn gauge for
        its tier. Router semantics are stricter than the replica's: a
        request burns budget unless it **completed within deadline** —
        rejections, unavailability, and failed dispatches all count,
        because the client experienced a miss either way."""
        tier = tier or "interactive"
        with self._lock:
            burn = self._burn.get(tier)
            if burn is None:
                burn = self._burn[tier] = BurnRate()
        burn.observe(missed)
        _registry.get_registry().gauge(
            "fleet", f"slo_burn_{tier}"
        ).set(burn.ewma)

    def _bump(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        self._counters[name].inc()

    def _note(self, rank: int, event: str) -> None:
        with self._lock:
            row = self._per_replica.setdefault(rank, {
                "dispatched": 0, "completed": 0, "refused": 0,
                "backpressure": 0, "failed": 0, "lost": 0,
            })
            row[event] = row.get(event, 0) + 1

    def ledger(self) -> dict:
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "unavailable": self.unavailable,
                "failed": self.failed,
            }
        out["in_flight"] = (
            out["submitted"] - out["completed"] - out["rejected"]
            - out["unavailable"] - out["failed"]
        )
        return out

    def check_conservation(self, *, in_flight: int = 0) -> dict:
        """Router-side conservation law — every submitted request is
        accounted for in exactly one terminal counter."""
        ledger = self.ledger()
        if ledger["in_flight"] != in_flight:
            raise AssertionError(
                f"fleet conservation violated: expected in_flight="
                f"{in_flight}, ledger says {ledger}"
            )
        return ledger

    def stats(self) -> dict:
        with self._lock:
            per_replica = {r: dict(v) for r, v in self._per_replica.items()}
            down = sorted(self._down)
            slo = {tier: b.snapshot() for tier, b in sorted(self._burn.items())}
        return {
            "policy": self.policy,
            "ledger": self.ledger(),
            "retries": self.retries,
            "per_replica": per_replica,
            "down": down,
            # Router-observed burn (every routed outcome) next to the
            # scrape-side rollup of what each replica's engine saw —
            # disagreement between the two is itself a signal (e.g. the
            # router burning on "unavailable" while replicas look clean).
            "slo": slo,
            "slo_fleet": fleet_slo_rollup(self._snapshot_source()),
            "admission": self.admission.stats(),
            "affinity": self.affinity.stats(),
        }
