"""SLO-tier admission — the fleet-level gate in front of every replica queue.

Each replica already carries its own ``RequestQueue`` with the
``Backpressure``/retry-after contract (``serving/queue.py``); this layer
adds what a *fleet* needs before a request is allowed to touch any
replica at all:

- **SLO tiers** — named service classes with their own default deadline
  and concurrency budget. ``interactive`` is small-budget/short-deadline
  (latency protected by never letting batch traffic monopolize the
  fleet); ``batch`` is big-budget/long-deadline. A tier at its
  concurrency budget rejects with the same retry-after shape the replica
  queue uses, so clients need one backoff discipline, not two.
- **Per-tenant quotas** — a cap on any single tenant's concurrent
  in-flight requests, so one noisy tenant exhausts its own quota, not
  the fleet.

Admission hands out a :class:`Lease`; the router releases it when the
request reaches any terminal state. retry-after is estimated from an
EWMA of observed service time (the same feedback idea as
``RequestQueue``): "one service-time per queued-ahead slot" — honest
enough to spread thundering herds.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from machine_learning_apache_spark_tpu.serving.queue import Backpressure


class FleetBackpressure(Backpressure):
    """Fleet-level 429: the tier or tenant budget is exhausted. Subclass
    of the replica-queue ``Backpressure`` on purpose — callers already
    handling retry-after handle this one for free. ``scope`` says which
    budget pushed back ("tier:interactive", "tenant:acme")."""

    def __init__(self, depth: int, retry_after: float, scope: str):
        super().__init__(depth, retry_after)
        self.scope = scope

    def __str__(self) -> str:
        return (
            f"fleet admission rejected ({self.scope} at depth "
            f"{self.depth}); retry after {self.retry_after:.3f}s"
        )


@dataclass(frozen=True)
class SLOTier:
    """One service class. ``deadline_s`` is the default per-request
    deadline stamped on submission (a caller's explicit deadline wins);
    ``max_in_flight`` bounds the tier's concurrent admissions across the
    whole fleet."""

    name: str
    deadline_s: float
    max_in_flight: int

    def __post_init__(self):
        if self.deadline_s <= 0:
            raise ValueError(
                f"tier {self.name!r}: deadline_s must be > 0, "
                f"got {self.deadline_s}"
            )
        if self.max_in_flight < 1:
            raise ValueError(
                f"tier {self.name!r}: max_in_flight must be >= 1, "
                f"got {self.max_in_flight}"
            )


def default_tiers() -> dict[str, SLOTier]:
    """The stock two-tier policy, env-tunable without code
    (``MLSPARK_FLEET_<TIER>_DEADLINE_S`` / ``_MAX_IN_FLIGHT``)."""

    def _f(name: str, default: float) -> float:
        return float(os.environ.get(name, default))

    def _i(name: str, default: int) -> int:
        return int(os.environ.get(name, default))

    return {
        "interactive": SLOTier(
            "interactive",
            deadline_s=_f("MLSPARK_FLEET_INTERACTIVE_DEADLINE_S", 10.0),
            max_in_flight=_i("MLSPARK_FLEET_INTERACTIVE_MAX_IN_FLIGHT", 64),
        ),
        "batch": SLOTier(
            "batch",
            deadline_s=_f("MLSPARK_FLEET_BATCH_DEADLINE_S", 120.0),
            max_in_flight=_i("MLSPARK_FLEET_BATCH_MAX_IN_FLIGHT", 256),
        ),
    }


@dataclass
class Lease:
    """Proof of admission; release exactly once."""

    tier: str
    tenant: str | None
    deadline_s: float
    released: bool = False


class FleetAdmission:
    """Thread-safe tier + tenant budget keeper."""

    def __init__(
        self,
        tiers: dict[str, SLOTier] | None = None,
        *,
        tenant_max_in_flight: int | None = None,
        clock=None,
    ):
        import time

        self.tiers = dict(tiers) if tiers is not None else default_tiers()
        if not self.tiers:
            raise ValueError("at least one SLO tier is required")
        if tenant_max_in_flight is None:
            from machine_learning_apache_spark_tpu.utils import env as envcfg

            tenant_max_in_flight = envcfg.get_int(
                "MLSPARK_FLEET_TENANT_MAX_IN_FLIGHT"
            )
        if tenant_max_in_flight is not None and tenant_max_in_flight < 1:
            raise ValueError(
                f"tenant_max_in_flight must be >= 1, got "
                f"{tenant_max_in_flight}"
            )
        self.tenant_max_in_flight = tenant_max_in_flight
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._tier_in_flight: dict[str, int] = {t: 0 for t in self.tiers}
        self._tenant_in_flight: dict[str, int] = {}
        # Temporary per-tier cap multipliers (0 < factor <= 1). The
        # autoscaler sheds the batch tier during a scale-down drain so
        # the shrinking fleet's headroom goes to interactive traffic.
        self._shed: dict[str, float] = {}
        # EWMA of observed fleet service time, seeding retry-after.
        self._service_ewma = 0.05
        self.admitted = 0
        self.rejected_tier = 0
        self.rejected_tenant = 0

    # -- the gate ------------------------------------------------------------
    def admit(
        self, *, tier: str = "interactive", tenant: str | None = None
    ) -> Lease:
        """Admit or raise :class:`FleetBackpressure` with a retry-after
        proportional to how oversubscribed the exhausted budget is."""
        slo = self.tiers.get(tier)
        if slo is None:
            raise ValueError(
                f"unknown SLO tier {tier!r} (have {sorted(self.tiers)})"
            )
        with self._lock:
            tier_depth = self._tier_in_flight[tier]
            cap = self._effective_cap_locked(tier, slo)
            if tier_depth >= cap:
                self.rejected_tier += 1
                raise FleetBackpressure(
                    tier_depth,
                    self._retry_after_locked(tier_depth, cap),
                    scope=f"tier:{tier}",
                )
            if tenant is not None and self.tenant_max_in_flight is not None:
                tdepth = self._tenant_in_flight.get(tenant, 0)
                if tdepth >= self.tenant_max_in_flight:
                    self.rejected_tenant += 1
                    raise FleetBackpressure(
                        tdepth,
                        self._retry_after_locked(
                            tdepth, self.tenant_max_in_flight
                        ),
                        scope=f"tenant:{tenant}",
                    )
            self._tier_in_flight[tier] = tier_depth + 1
            if tenant is not None:
                self._tenant_in_flight[tenant] = (
                    self._tenant_in_flight.get(tenant, 0) + 1
                )
            self.admitted += 1
        return Lease(tier=tier, tenant=tenant, deadline_s=slo.deadline_s)

    def release(self, lease: Lease, *, service_s: float | None = None) -> None:
        """Return the lease's budget; idempotent. ``service_s`` (time
        from dispatch to terminal state) feeds the retry-after EWMA."""
        with self._lock:
            if lease.released:
                return
            lease.released = True
            self._tier_in_flight[lease.tier] = max(
                0, self._tier_in_flight[lease.tier] - 1
            )
            if lease.tenant is not None:
                left = self._tenant_in_flight.get(lease.tenant, 0) - 1
                if left > 0:
                    self._tenant_in_flight[lease.tenant] = left
                else:
                    self._tenant_in_flight.pop(lease.tenant, None)
            if service_s is not None and service_s >= 0:
                self._service_ewma += 0.2 * (service_s - self._service_ewma)

    # -- shedding (the autoscaler's drain-time lever) ------------------------
    def shed(self, tier: str, factor: float) -> None:
        """Temporarily scale ``tier``'s concurrency cap by ``factor``
        (0 < factor <= 1). At most one shed per tier; re-shedding
        replaces the factor. The floor is a cap of 1 — shedding never
        closes a tier entirely."""
        if tier not in self.tiers:
            raise ValueError(
                f"unknown SLO tier {tier!r} (have {sorted(self.tiers)})"
            )
        if not 0.0 < factor <= 1.0:
            raise ValueError(f"shed factor must be in (0, 1], got {factor}")
        with self._lock:
            self._shed[tier] = factor

    def unshed(self, tier: str) -> None:
        """Restore ``tier``'s full concurrency cap; idempotent."""
        with self._lock:
            self._shed.pop(tier, None)

    def _effective_cap_locked(self, tier: str, slo: SLOTier) -> int:
        factor = self._shed.get(tier)
        if factor is None:
            return slo.max_in_flight
        return max(1, int(slo.max_in_flight * factor))

    def _retry_after_locked(self, depth: int, cap: int) -> float:
        # One EWMA service-time per slot we'd have to wait for, floored
        # so clients can't spin: same shape as RequestQueue's estimate.
        over = max(1, depth - cap + 1)
        return max(0.01, self._service_ewma * over)

    # -- introspection -------------------------------------------------------
    def service_ewma(self) -> float:
        """The raw observed-service-time EWMA (seconds) — the router's
        hedge-delay basis: a hedge fires only once a dispatch has been
        outstanding noticeably longer than a typical request takes."""
        with self._lock:
            return self._service_ewma

    def stats(self) -> dict:
        with self._lock:
            return {
                "tiers": {
                    name: {
                        "in_flight": self._tier_in_flight[name],
                        "max_in_flight": slo.max_in_flight,
                        "effective_max_in_flight": (
                            self._effective_cap_locked(name, slo)
                        ),
                        "shed_factor": self._shed.get(name),
                        "deadline_s": slo.deadline_s,
                    }
                    for name, slo in self.tiers.items()
                },
                "tenants_active": len(self._tenant_in_flight),
                "tenant_max_in_flight": self.tenant_max_in_flight,
                "admitted": self.admitted,
                "rejected_tier": self.rejected_tier,
                "rejected_tenant": self.rejected_tenant,
                "service_ewma_s": round(self._service_ewma, 4),
            }
