"""fleet/ — multi-replica serving data plane: N engines, one front door.

The serving engine (``serving/``) is a single process; the launcher
(``launcher/``) can spawn and supervise N of them; the observability
plane (``telemetry/http``) makes each one scrapeable. This package is
the layer that turns those N replicas into one service:

- :mod:`~.scrape` — the scrape data plane (promoted from
  ``tools/gang_status.py``): per-replica ``/healthz`` + ``/statusz``
  snapshots with retry/backoff, and a background :class:`~.scrape.ScrapeLoop`
  that follows replicas across restarts via their sidecar files;
- :mod:`~.affinity` — prefix-cache affinity: ``prefix_digest`` →
  candidate replicas, fed by routing memory and scraped residency;
- :mod:`~.admission` — SLO tiers (interactive vs batch deadlines) and
  per-tenant quotas on the ``Backpressure``/retry-after contract;
- :mod:`~.router` — health-aware dispatch (affinity-first, least-loaded
  fallback, round-robin baseline) that drains around 503s and keeps a
  conservation ledger over every routed request;
- :mod:`~.replica` — the per-rank data plane: ``POST /v1/generate``
  over one engine plus the delegated observability GET endpoints, and
  ``serve_replica`` as the launcher-gang worker body;
- :mod:`~.autoscaler` — the closed loop over all of the above:
  :class:`~.autoscaler.FleetAutoscaler` watches scrape snapshots and
  resizes the ``ReplicaGang`` (SLO burn / queue depth up, coldest-
  replica drain down, exhausted ranks absorbed as observed
  scale-downs), logging every decision as a ``fleet.autoscaler``
  annotation.

Replica gangs with *per-rank* restart (vs the Distributor's
all-or-nothing barrier semantics) live in
``launcher.replica_gang.ReplicaGang``. Env contract: ``MLSPARK_FLEET_*``
(see docs/FLEET.md).
"""

from machine_learning_apache_spark_tpu.fleet.admission import (
    FleetAdmission,
    FleetBackpressure,
    Lease,
    SLOTier,
    default_tiers,
)
from machine_learning_apache_spark_tpu.fleet.affinity import (
    AffinityTable,
    prefix_digest,
)
from machine_learning_apache_spark_tpu.fleet.autoscaler import (
    AutoscaleConfig,
    FleetAutoscaler,
)
from machine_learning_apache_spark_tpu.fleet.replica import (
    ReplicaServer,
    serve_replica,
    write_fleet_sidecar,
)
from machine_learning_apache_spark_tpu.fleet.router import (
    POLICIES,
    FleetRequestFailed,
    FleetRouter,
    FleetUnavailable,
    ReplicaClient,
    pick_replica,
)
from machine_learning_apache_spark_tpu.fleet.scrape import (
    ReplicaSnapshot,
    ScrapeLoop,
    find_fleet_sidecars,
    scrape,
    snapshot_replica,
)

__all__ = [
    "AffinityTable",
    "AutoscaleConfig",
    "FleetAdmission",
    "FleetAutoscaler",
    "FleetBackpressure",
    "FleetRequestFailed",
    "FleetRouter",
    "FleetUnavailable",
    "Lease",
    "POLICIES",
    "ReplicaClient",
    "ReplicaServer",
    "ReplicaSnapshot",
    "SLOTier",
    "ScrapeLoop",
    "default_tiers",
    "find_fleet_sidecars",
    "pick_replica",
    "prefix_digest",
    "scrape",
    "serve_replica",
    "snapshot_replica",
    "write_fleet_sidecar",
]
