"""StreamingPipeline — records to device-resident batches, off the hot path.

The stage chain (each optional stage collapses to a pass-through):

    source -> [transform] -> [online pack] -> shard -> batch/tail-policy
           -> [bounded host prefetch thread] -> [double-buffered device put]

Everything left of the prefetch queue runs on a background producer
thread; the consumer (the training loop) pulls host batches from a
bounded queue and enqueues ``jax.device_put`` (or the mesh-sharded
``shard_batch``) ``device_prefetch`` batches ahead, so batch k+1 is
device-resident before step k's async dispatch returns. The queue bound
caps host memory; shutdown is clean — ``shutdown()`` (called by ``fit``'s
finally) releases the producer and joins it, leaving no threads behind.

**Batch-count equalization** (the gang-deadlock fix): every rank MUST
yield the same number of batches per epoch or the epoch-tail collective
hangs. Two shard modes, two guarantees:

- ``shard="records"`` (default): every rank enumerates the same global
  unit stream (records, or packed rows when packing is on) and keeps
  units ``i % world == rank``. Per-rank counts differ by at most one and
  every rank knows the global count N at end of stream, so the tail
  policy is computed from N identically everywhere: ``tail="pad"`` wraps
  each rank's own recent units to ``ceil(ceil(N/world)/B)`` batches
  (the ``DistributedSampler`` convention); ``tail="drop"`` truncates every
  rank to ``(N // world) // B`` (a one-batch holdback keeps a rank with a
  surplus unit from over-yielding before N is known).
- ``shard="files"``: rank r reads only ``paths[r::world]`` (a true I/O
  split; per-rank record counts are ragged and no rank knows N), so a
  fixed ``steps_per_epoch`` is REQUIRED for world > 1: every rank yields
  exactly that many batches, wrapping its local stream when short.

Record-level sharding duplicates read/parse work across ranks in
exchange for the guarantee and for global-stream determinism (mixture
sampling needs every rank to see the same draw sequence); file-level
sharding is the scalable path when the file set is large. See
docs/DATA.md for the decision table.

Telemetry: every stage reports into the ``data.*`` family —
``data.read`` / ``data.pack`` / ``data.h2d`` phase durations (per batch),
``data.wait`` (consumer time blocked on the host buffer — the direct
input-bound signal), a ``data.buffer_occupancy`` gauge sampled at every
producer put, and per-epoch ``data.records`` / ``data.batches`` /
``data.bytes_h2d`` counters. ``telemetry.aggregate.ingest_report`` folds
these into the gang report's input-bound/compute-bound verdict.
"""

from __future__ import annotations

import collections
import queue as _queue
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.ingest.config import IngestConfig
from machine_learning_apache_spark_tpu.ingest.packing import OnlinePacker
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: Thread-name prefix for every pipeline worker — the leak check in
#: tests (and operators' py-spy dumps) find them by this.
WORKER_PREFIX = "mlspark-ingest"

_END, _ERR = object(), object()

SHARD_MODES = ("records", "files")

_PACK_KEYS = {"src_len", "trg_len", "pad_id", "max_segments"}


def _default_collate(units: list) -> Any:
    """Stack per-field: a list of B record tuples becomes a tuple of
    ``[B, ...]`` arrays (scalar fields stack to ``[B]`` vectors)."""
    first = units[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([u[i] for u in units]) for i in range(len(first))
        )
    return np.stack(units)


def _env_int(name: str, default: int) -> int:
    import os

    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _emit_phase(name: str, seconds: float, **attrs) -> None:
    """Record a phase duration as a ``span_end`` event so the aggregate
    phase table picks it up. Producer-side phases are accumulated per
    batch (per-record spans would flood the bounded event ring)."""
    telemetry.get_log().emit(
        "span_end", name, value=seconds, attrs=attrs or None
    )


class _UnitStream:
    """One pass over the pipeline's global unit stream: applies transform
    and online packing, filters to this rank's units (records mode), and
    accumulates read/pack time for the per-batch phase events. After
    exhaustion, ``global_units`` holds the pass's total unit count (global
    in records mode, local in files mode) and ``records_read`` the number
    of records pulled from the source."""

    def __init__(self, pipeline: "StreamingPipeline") -> None:
        self.pl = pipeline
        self.read_seconds = 0.0
        self.pack_seconds = 0.0
        self.records_read = 0
        self.global_units = 0

    def __iter__(self) -> Iterator:
        pl = self.pl
        perf = time.perf_counter
        filt = pl.shard == "records" and pl.world > 1
        rank, world = pl.rank, pl.world
        packer = OnlinePacker(**pl.pack) if pl.pack is not None else None
        transform = pl.transform
        idx = 0  # unit index within the (global) stream
        it = iter(pl._source)
        while True:
            t0 = perf()
            try:
                rec = next(it)
            except StopIteration:
                self.read_seconds += perf() - t0
                break
            if transform is not None:
                rec = transform(rec)
            self.read_seconds += perf() - t0
            self.records_read += 1
            if packer is None:
                if not filt or idx % world == rank:
                    yield rec
                idx += 1
            else:
                t1 = perf()
                row = packer.add(rec[0], rec[1])
                self.pack_seconds += perf() - t1
                if row is not None:
                    if not filt or idx % world == rank:
                        yield row
                    idx += 1
        if packer is not None:
            t1 = perf()
            row = packer.flush()
            self.pack_seconds += perf() - t1
            if row is not None:
                if not filt or idx % world == rank:
                    yield row
                idx += 1
        self.global_units = idx


class StreamingPipeline:
    """Async streaming input pipeline; the ``data=`` argument of
    ``train.loop.fit``.

    - ``source``: any ``ingest.readers`` source, a ``MixtureSampler``, or
      a plain restartable iterable of records.
    - ``batch_size``: records (or packed rows) per batch — the static
      leading dimension.
    - ``rank``/``world``: gang coordinates; default from the launcher env
      contract (``MLSPARK_PROCESS_ID`` / ``MLSPARK_NUM_PROCESSES``).
    - ``shard``/``tail``/``steps_per_epoch``: see the module docstring's
      equalization contract.
    - ``transform``: per-record callable applied in the producer thread
      (tokenize-outside-the-step seam).
    - ``pack``: ``dict(src_len=, trg_len=, pad_id=, max_segments=)``
      enables online packing; records must then be (src_ids, trg_ids)
      pairs and batches are stacked 6-tuples of packed rows.
    - ``buffer``/``device_prefetch``: queue depths, resolved through
      ``MLSPARK_INGEST_*`` when not given (``IngestConfig.from_env``).
    - ``mesh``/``device``: device placement — mesh-sharded when a mesh is
      bound (``fit`` binds its own), plain ``jax.device_put`` otherwise;
      ``device=False`` yields host batches.
    """

    #: duck-typing marker for fit() — avoids an import cycle.
    is_streaming_pipeline = True

    def __init__(
        self,
        source,
        batch_size: int,
        *,
        rank: int | None = None,
        world: int | None = None,
        shard: str = "records",
        tail: str | None = None,
        steps_per_epoch: int | None = None,
        transform: Callable | None = None,
        collate: Callable[[list], Any] | None = None,
        pack: dict | None = None,
        buffer: int | None = None,
        device_prefetch: int | None = None,
        mesh=None,
        device: bool = True,
        name: str = "train",
    ) -> None:
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if shard not in SHARD_MODES:
            raise ValueError(
                f"unknown shard mode {shard!r} (expected one of {SHARD_MODES})"
            )
        if steps_per_epoch is not None and steps_per_epoch < 1:
            raise ValueError(
                f"steps_per_epoch must be >= 1, got {steps_per_epoch}"
            )
        self.config = IngestConfig.from_env(
            buffer=buffer, device_prefetch=device_prefetch, tail=tail
        )
        self.batch_size = batch_size
        self.rank = rank if rank is not None else _env_int(
            "MLSPARK_PROCESS_ID", 0
        )
        self.world = world if world is not None else _env_int(
            "MLSPARK_NUM_PROCESSES", 1
        )
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"rank {self.rank} outside world of {self.world}"
            )
        self.shard = shard
        self.steps_per_epoch = steps_per_epoch
        self.transform = transform
        self.collate = collate or _default_collate
        if pack is not None:
            unknown = set(pack) - _PACK_KEYS
            if unknown:
                raise ValueError(
                    f"unknown pack option(s) {sorted(unknown)} "
                    f"(expected a subset of {sorted(_PACK_KEYS)})"
                )
            OnlinePacker(**pack)  # validate budgets now, not mid-epoch
        self.pack = dict(pack) if pack is not None else None
        self.mesh = mesh
        self.device = device
        self.name = name
        if shard == "files":
            if not hasattr(source, "shard_files"):
                raise ValueError(
                    f"shard='files' needs a file-backed source with "
                    f"shard_files(); {type(source).__name__} has none — "
                    "use shard='records'"
                )
            if self.world > 1:
                if steps_per_epoch is None:
                    raise ValueError(
                        "shard='files' with world > 1 requires "
                        "steps_per_epoch: ranks read disjoint files, so no "
                        "rank knows the global record count and only a "
                        "fixed per-epoch step budget keeps batch counts "
                        "equal across the gang (gang collectives deadlock "
                        "otherwise)"
                    )
                source = source.shard_files(self.rank, self.world)
        self._source = source
        self._epoch = 0
        self._workers: list[tuple[threading.Event, threading.Thread, Any]] = []
        #: batches yielded in the most recently completed epoch.
        self.last_epoch_batches: int | None = None

    # -- epoch / fit integration --------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        if hasattr(self._source, "set_epoch"):
            self._source.set_epoch(epoch)

    def bind(self, *, mesh=None, device: bool | None = None) -> None:
        """Late-bind device placement (``fit`` passes its mesh here; the
        scanned ``steps_per_call`` path binds ``device=False`` because it
        stacks host batches itself)."""
        if mesh is not None:
            self.mesh = mesh
        if device is not None:
            self.device = device

    @property
    def yields_device_batches(self) -> bool:
        return self.device and self.config.device_prefetch > 0

    # -- resume state --------------------------------------------------------
    def state_dict(self) -> dict:
        """JSON-safe pipeline position for the checkpoint meta sidecar:
        the epoch counter plus the source's stream state (mixture RNG and
        cursors) when the source is stateful."""
        sd: dict = {"version": 1, "epoch": self._epoch}
        if hasattr(self._source, "state_dict"):
            sd["source"] = self._source.state_dict()
        return sd

    def load_state_dict(self, sd: dict) -> None:
        self._epoch = int(sd.get("epoch", 0))
        src_state = sd.get("source")
        if src_state is not None:
            if not hasattr(self._source, "load_state_dict"):
                raise ValueError(
                    "checkpoint carries ingest source state but "
                    f"{type(self._source).__name__} cannot restore it — "
                    "resuming would silently replay a different stream"
                )
            self._source.load_state_dict(src_state)

    # -- iteration -----------------------------------------------------------
    def __iter__(self) -> Iterator:
        it = self._host_batches()
        if self.config.buffer > 0:
            it = self._prefetched(it)
        if self.yields_device_batches:
            it = self._device_stage(it)
        return it

    def _host_batches(self) -> Iterator:
        B = self.batch_size
        target = self.steps_per_epoch
        tail = self.config.tail
        epoch = self._epoch
        eq_world = self.world if self.shard == "records" else 1
        yielded = 0
        pending = None  # drop-policy holdback (see module docstring)
        buf: list = []
        # Wrap-pad material: a rank's most recent units, enough to fill
        # one batch — bounded, unlike retaining the shard.
        recent: collections.deque = collections.deque(maxlen=B)
        records_acc = 0
        stream: _UnitStream | None = None

        def _batch_of(units: list):
            t0 = time.perf_counter()
            out = self.collate(units)
            if telemetry.enabled() and stream is not None:
                _emit_phase(
                    "data.read",
                    stream.read_seconds + (time.perf_counter() - t0),
                    epoch=epoch,
                )
                stream.read_seconds = 0.0
                if self.pack is not None:
                    _emit_phase("data.pack", stream.pack_seconds, epoch=epoch)
                    stream.pack_seconds = 0.0
            return out

        try:
            while True:  # >1 pass only when steps_per_epoch wraps the stream
                stream = _UnitStream(self)
                pass_units = 0
                for unit in stream:
                    pass_units += 1
                    buf.append(unit)
                    recent.append(unit)
                    if len(buf) == B:
                        batch = _batch_of(buf)
                        buf = []
                        if target is None and tail == "drop":
                            if pending is not None:
                                yield pending
                                yielded += 1
                            pending = batch
                        else:
                            yield batch
                            yielded += 1
                            if target is not None and yielded >= target:
                                return
                records_acc += stream.records_read
                stream.records_read = 0  # folded; finally must not re-add
                if target is None:
                    break
                if pass_units == 0:
                    raise ValueError(
                        f"ingest source yielded no units on a full pass; "
                        f"cannot reach steps_per_epoch={target}"
                    )
                stream = None  # records already folded into records_acc
            # Natural end of the stream: equalize the epoch tail from the
            # unit count every rank observed identically.
            n = stream.global_units
            if tail == "drop":
                allowed = (n // eq_world) // B
                if pending is not None and yielded < allowed:
                    yield pending
                    yielded += 1
                pending = None
            else:  # pad
                per_rank = -(-n // eq_world)  # ceil
                target_pad = -(-per_rank // B)
                fill = list(buf)
                buf = []
                ring = list(recent)
                if yielded < target_pad and not ring:
                    raise ValueError(
                        f"rank {self.rank} saw no units this epoch but the "
                        f"gang-wide batch target is {target_pad}; the "
                        f"dataset ({n} unit(s)) is smaller than the world "
                        f"size {eq_world}"
                    )
                i = 0
                while yielded < target_pad:
                    while len(fill) < B:
                        fill.append(ring[i % len(ring)])
                        i += 1
                    yield _batch_of(fill[:B])
                    fill = fill[B:]
                    yielded += 1
        finally:
            if stream is not None:
                records_acc += stream.records_read
            self.last_epoch_batches = yielded
            reg = telemetry.get_registry()
            reg.counter("data", "records").inc(records_acc)
            reg.counter("data", "batches").inc(yielded)
            if telemetry.enabled():
                log_ = telemetry.get_log()
                log_.emit(
                    "counter", "data.records", value=float(records_acc),
                    attrs={"epoch": epoch},
                )
                log_.emit(
                    "counter", "data.batches", value=float(yielded),
                    attrs={"epoch": epoch},
                )

    def _prefetched(self, it: Iterator) -> Iterator:
        """Bounded producer/consumer stage: batch assembly moves to a
        background thread; the queue bound caps host memory. Same
        stop-event/sentinel shutdown discipline as ``data.loader``'s
        prefetcher, plus occupancy telemetry and a join on teardown (no
        leaked threads — pinned by tests/test_ingest.py)."""
        q: _queue.Queue = _queue.Queue(maxsize=self.config.buffer)
        stop = threading.Event()
        gauge = telemetry.get_registry().gauge("data", "buffer_occupancy")

        def _put(item) -> bool:
            # Bounded-wait put: an abandoned consumer releases the worker
            # within 100ms of shutdown() setting the stop event.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker():
            try:
                for item in it:
                    if not _put(item):
                        return
                    occ = q.qsize()
                    gauge.set(occ)
                    if telemetry.enabled():
                        telemetry.get_log().emit(
                            "gauge", "data.buffer_occupancy", value=float(occ)
                        )
            except BaseException as e:  # re-raised at the consumer
                _put((_ERR, e))
            else:
                _put(_END)

        thread = threading.Thread(
            target=worker,
            daemon=True,
            name=f"{WORKER_PREFIX}-{self.name}-e{self._epoch}",
        )
        handle = (stop, thread, q)
        self._workers.append(handle)
        thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                while True:
                    try:
                        item = q.get(timeout=1.0)
                        break
                    except _queue.Empty:
                        if not thread.is_alive():
                            raise RuntimeError(
                                "ingest producer thread died without a "
                                "sentinel (killed?)"
                            ) from None
                wait = time.perf_counter() - t0
                if item is _END:
                    return
                if (
                    isinstance(item, tuple)
                    and len(item) == 2
                    and item[0] is _ERR
                ):
                    raise item[1]
                if telemetry.enabled():
                    _emit_phase("data.wait", wait, epoch=self._epoch)
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            thread.join(timeout=5.0)
            if handle in self._workers:
                self._workers.remove(handle)

    def _device_stage(self, it: Iterator) -> Iterator:
        """Double-buffered device placement: enqueue ``device_prefetch``
        transfers ahead of consumption, so H2D overlaps device compute
        (transfers are async; the span measures enqueue cost)."""
        import jax

        from machine_learning_apache_spark_tpu.parallel.mesh import (
            shard_batch,
        )

        depth = max(self.config.device_prefetch, 1)
        pending: collections.deque = collections.deque()
        h2d_counter = telemetry.get_registry().counter("data", "bytes_h2d")
        bytes_total = 0
        try:
            for batch in it:
                nbytes = sum(
                    x.nbytes
                    for x in jax.tree.leaves(batch)
                    if hasattr(x, "nbytes")
                )
                t0 = time.perf_counter()
                dev = (
                    shard_batch(self.mesh, batch)
                    if self.mesh is not None
                    else jax.device_put(batch)
                )
                if telemetry.enabled():
                    _emit_phase(
                        "data.h2d", time.perf_counter() - t0,
                        epoch=self._epoch,
                    )
                h2d_counter.inc(nbytes)
                bytes_total += nbytes
                pending.append(dev)
                if len(pending) >= depth:
                    yield pending.popleft()
            while pending:
                yield pending.popleft()
        finally:
            if telemetry.enabled() and bytes_total:
                telemetry.get_log().emit(
                    "counter", "data.bytes_h2d", value=float(bytes_total),
                    attrs={"epoch": self._epoch},
                )

    # -- teardown ------------------------------------------------------------
    def shutdown(self) -> None:
        """Release and join every live producer thread (idempotent; safe
        mid-epoch). ``fit`` calls this in its finally, so a training run
        leaves no pipeline threads behind whether it returned or raised."""
        handles, self._workers = self._workers, []
        for stop, _, _ in handles:
            stop.set()
        for _, thread, q in handles:
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            thread.join(timeout=5.0)
            if thread.is_alive():
                log.warning(
                    "ingest worker %s did not exit within 5s", thread.name
                )

    def __enter__(self) -> "StreamingPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


def rescatter_stream_state(
    sd: dict, *, old_world: int, new_world: int, shard: str = "records"
) -> dict:
    """Validate and adapt a pipeline ``state_dict`` across a world-size
    change (elastic resume, ``train/reshard.py``).

    In ``shard="records"`` mode the sidecar state is rank-agnostic by
    construction — every rank strides the same stream by its own
    ``(rank, world)`` read from the env at pipeline construction, and
    batch-count equalization is recomputed per-iteration from the
    CURRENT world — so the rescatter is adopt-as-is; this function's job
    is pinning that contract (and failing the one case that breaks it).
    ``shard="files"`` partitions FILES per rank at construction, so a
    saved cursor indexes into one old rank's file subset and cannot be
    re-scattered without re-reading the old partition; elastic resume
    refuses it loudly rather than silently replaying the wrong files.
    """
    if int(new_world) < 1 or int(old_world) < 1:
        raise ValueError(
            f"world sizes must be >= 1, got {old_world} -> {new_world}"
        )
    if shard == "files":
        raise ValueError(
            "ingest stream state from shard='files' is rank-local (each "
            f"rank cursors its own file subset) and cannot be re-scattered "
            f"from world {old_world} to world {new_world}; use "
            "shard='records' for elastic runs or drop the ingest state"
        )
    out = dict(sd)
    out["rescattered"] = {"old_world": int(old_world), "new_world": int(new_world)}
    return out
