"""Ingest env contract — ``MLSPARK_INGEST_*`` resolution.

Same precedence rule as the rest of the stack (``parallel.zero``):
explicit argument > environment variable > default. The launcher's
``Distributor(ingest={...})`` knob writes these variables into every
worker's environment (like ``MLSPARK_DP_MODE``), so a driver script
configures the gang's input pipeline in one place and each rank's
``StreamingPipeline`` picks it up at construction.

Stdlib-only: imported by the launcher before JAX platform selection.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

#: knob -> (env var, parser, validator description). The single source of
#: truth for Distributor validation and IngestConfig.from_env.
ENV_PREFIX = "MLSPARK_INGEST_"

TAIL_POLICIES = ("pad", "drop")

#: Knobs the launcher accepts in ``Distributor(ingest={...})``.
INGEST_KNOBS = ("buffer", "device_prefetch", "tail", "chunk_lines")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(ENV_PREFIX + name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(
            f"{ENV_PREFIX}{name} must be an integer, got {v!r}"
        ) from None


@dataclass(frozen=True)
class IngestConfig:
    """Resolved input-pipeline knobs.

    - ``buffer``: host-side prefetch depth in batches (the bounded
      producer/consumer queue). 0 = synchronous batch assembly.
    - ``device_prefetch``: batches kept resident on-device ahead of
      consumption (double buffering at the default 2). 0 disables the
      device stage (the pipeline yields host arrays).
    - ``tail``: epoch-tail policy, ``"pad"`` (wrap-pad the final batch so
      every rank yields the same count — collective-safe default, the
      ``DistributedSampler`` convention) or ``"drop"`` (drop ragged
      tails; still rank-equalized, see ``ingest.pipeline``).
    - ``chunk_lines``: lines per parser call in the streaming file
      readers (the native-parser batching grain).
    """

    buffer: int = 2
    device_prefetch: int = 2
    tail: str = "pad"
    chunk_lines: int = 1024

    def __post_init__(self):
        if self.buffer < 0:
            raise ValueError(f"ingest buffer must be >= 0, got {self.buffer}")
        if self.device_prefetch < 0:
            raise ValueError(
                f"ingest device_prefetch must be >= 0, got "
                f"{self.device_prefetch}"
            )
        if self.tail not in TAIL_POLICIES:
            raise ValueError(
                f"unknown ingest tail policy {self.tail!r} "
                f"(expected one of {TAIL_POLICIES})"
            )
        if self.chunk_lines < 1:
            raise ValueError(
                f"ingest chunk_lines must be >= 1, got {self.chunk_lines}"
            )

    @classmethod
    def from_env(
        cls,
        *,
        buffer: int | None = None,
        device_prefetch: int | None = None,
        tail: str | None = None,
        chunk_lines: int | None = None,
    ) -> "IngestConfig":
        """Explicit argument > ``MLSPARK_INGEST_*`` env > default."""
        return cls(
            buffer=buffer if buffer is not None else _env_int("BUFFER", 2),
            device_prefetch=(
                device_prefetch
                if device_prefetch is not None
                else _env_int("DEVICE_PREFETCH", 2)
            ),
            tail=tail if tail is not None else os.environ.get(
                ENV_PREFIX + "TAIL", "pad"
            ),
            chunk_lines=(
                chunk_lines
                if chunk_lines is not None
                else _env_int("CHUNK_LINES", 1024)
            ),
        )


def validate_ingest_knobs(knobs: dict) -> dict[str, str]:
    """Launcher-side validation of ``Distributor(ingest={...})``: unknown
    keys and bad values fail at Distributor construction, not inside every
    worker after rendezvous. Returns the ``{env var: value}`` mapping to
    write into worker environments."""
    out: dict[str, str] = {}
    for key, value in knobs.items():
        if key not in INGEST_KNOBS:
            raise ValueError(
                f"unknown ingest knob {key!r} (expected one of {INGEST_KNOBS})"
            )
        if key == "tail":
            if value not in TAIL_POLICIES:
                raise ValueError(
                    f"unknown ingest tail policy {value!r} "
                    f"(expected one of {TAIL_POLICIES})"
                )
        else:
            try:
                value = int(value)
            except (TypeError, ValueError):
                raise ValueError(
                    f"ingest knob {key!r} must be an integer, got {value!r}"
                ) from None
            if value < 0:
                raise ValueError(
                    f"ingest knob {key!r} must be >= 0, got {value}"
                )
        out[ENV_PREFIX + key.upper()] = str(value)
    return out
