"""Weighted mixture sampling over named record sources.

Multi-task runs draw each record from one of several datasets with fixed
probabilities — the Spark-era analogue is a weighted union of DataFrames.
Determinism contract: the draw sequence is a pure function of the seed
and the sampler's captured state, so (a) every rank constructing the same
mixture sees the SAME global record stream (required by record-level
sharding — the pipeline filters that shared stream by index), and (b)
``state_dict()``/``load_state_dict()`` round-trips through the checkpoint
meta sidecar replay the identical batch sequence after ``fit(resume=True)``.

Source iterators persist across epochs and cycle on exhaustion (an
"epoch" is ``records_per_epoch`` draws, not a pass over any one source),
so the RNG state + per-source draw counts fully describe the stream
position.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Sequence

import numpy as np


class MixtureSampler:
    """Acts as a record source for ``StreamingPipeline``: one pass yields
    exactly ``records_per_epoch`` records, each drawn from a source chosen
    by a seeded RNG with the given weights.

    ``sources`` is name -> restartable iterable (any ``ingest.readers``
    source). ``weights`` aligns with the insertion order of ``sources``
    (uniform when None); they are normalized internally.
    """

    def __init__(
        self,
        sources: Mapping[str, object],
        weights: Sequence[float] | None = None,
        *,
        records_per_epoch: int,
        seed: int = 0,
        name: str = "mixture",
    ) -> None:
        if not sources:
            raise ValueError("need at least one source")
        if records_per_epoch < 1:
            raise ValueError(
                f"records_per_epoch must be >= 1, got {records_per_epoch}"
            )
        self.names = list(sources)
        self.sources = dict(sources)
        if weights is None:
            weights = [1.0] * len(self.names)
        if len(weights) != len(self.names):
            raise ValueError(
                f"{len(self.names)} sources but {len(weights)} weights"
            )
        w = np.asarray(weights, dtype=np.float64)
        if (w < 0).any() or w.sum() <= 0:
            raise ValueError(f"weights must be >= 0 with a positive sum: {w}")
        self.weights = w / w.sum()
        self.records_per_epoch = records_per_epoch
        self.seed = seed
        self.name = name
        self._rng = np.random.default_rng(seed)
        self._iters: dict[str, Iterator | None] = {n: None for n in self.names}
        self._draws = {n: 0 for n in self.names}
        self._cycles = {n: 0 for n in self.names}

    def _next_from(self, name: str):
        it = self._iters[name]
        if it is None:
            it = iter(self.sources[name])
        try:
            rec = next(it)
        except StopIteration:
            it = iter(self.sources[name])
            self._cycles[name] += 1
            try:
                rec = next(it)
            except StopIteration:
                raise ValueError(f"mixture source {name!r} is empty") from None
        self._iters[name] = it
        self._draws[name] += 1
        return rec

    def __iter__(self) -> Iterator:
        for _ in range(self.records_per_epoch):
            k = int(self._rng.choice(len(self.names), p=self.weights))
            yield self._next_from(self.names[k])

    # -- resume state --------------------------------------------------------
    def state_dict(self) -> dict:
        """JSON-safe stream position: RNG bit-generator state + per-source
        draw counts (the cursor each source iterator must be advanced to)."""
        return {
            "rng": self._rng.bit_generator.state,
            "draws": dict(self._draws),
            "cycles": dict(self._cycles),
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore a ``state_dict()`` capture: the RNG resumes mid-stream
        and each source iterator is fast-forwarded to its recorded cursor
        by replaying (and discarding) its draws — sources only need to be
        restartable, not seekable."""
        rng = np.random.default_rng(self.seed)
        rng.bit_generator.state = state["rng"]
        self._rng = rng
        self._iters = {n: None for n in self.names}
        self._draws = {n: 0 for n in self.names}
        self._cycles = {n: 0 for n in self.names}
        for name in self.names:
            for _ in range(int(state["draws"].get(name, 0))):
                self._next_from(name)
        # Replay reproduces the draw counts; cycles follow from them, but
        # trust the recorded value in case a source length changed (which
        # would be a caller bug — still, never resume with silently
        # inconsistent bookkeeping).
        recorded = state.get("cycles") or {}
        for name, cycles in recorded.items():
            if name in self._cycles and self._cycles[name] != cycles:
                raise ValueError(
                    f"mixture source {name!r} replayed {self._cycles[name]} "
                    f"cycle(s) but the checkpoint recorded {cycles} — source "
                    "contents changed since the checkpoint was written"
                )
