"""ingest/ — async streaming input pipeline (the Spark-ingestion layer).

The reference's premise is Spark feeding accelerator training; this
subsystem is that layer rebuilt TPU-native: sharded streaming readers
(``readers``, native C++ parsers with pure-Python fallbacks), online
sequence packing in the loader thread (``packing``), weighted
deterministic mixture sampling (``mixture``), and the bounded
prefetch-to-device pipeline that ties them together (``pipeline``) —
``fit(data=StreamingPipeline(...))`` trains with batch k+1 device-resident
before step k's dispatch returns.

Env contract: ``MLSPARK_INGEST_*`` (``config``), plumbed through the
launcher via ``Distributor(ingest={...})``. Telemetry: the ``data.*``
span/counter family; ``tools/telemetry_report.py`` renders it and
classifies runs input-bound vs compute-bound. See docs/DATA.md.
"""

from machine_learning_apache_spark_tpu.ingest.config import (
    IngestConfig,
    validate_ingest_knobs,
)
from machine_learning_apache_spark_tpu.ingest.mixture import MixtureSampler
from machine_learning_apache_spark_tpu.ingest.packing import OnlinePacker
from machine_learning_apache_spark_tpu.ingest.pipeline import (
    StreamingPipeline,
    WORKER_PREFIX,
    rescatter_stream_state,
)
from machine_learning_apache_spark_tpu.ingest.readers import (
    ArraySource,
    CallableSource,
    EncodedTextSource,
    LibsvmStreamSource,
    PairSource,
    TextLineSource,
)

__all__ = [
    "ArraySource",
    "CallableSource",
    "EncodedTextSource",
    "IngestConfig",
    "LibsvmStreamSource",
    "MixtureSampler",
    "OnlinePacker",
    "PairSource",
    "StreamingPipeline",
    "TextLineSource",
    "WORKER_PREFIX",
    "rescatter_stream_state",
    "validate_ingest_knobs",
]
