"""Streaming record sources — files in, records out, nothing materialized.

The reference's premise is Spark as the ingestion layer: executors stream
partitions of a distributed file set into the training process. Our
``data/`` layer is the synchronous analogue (load everything, then
iterate); these sources are the streaming one. Each source is a
restartable iterable of *records* (small per-example pytrees, typically
tuples of numpy rows) that a ``StreamingPipeline`` shards, batches, and
prefetches — the whole dataset is never resident on the host.

File-backed sources parse through the native C++ fast paths
(``native/libsvm_parser.cpp``, ``native/text_encode.cpp``) one chunk of
lines at a time, with the same pure-Python fallbacks the synchronous
readers use; the chunk grain keeps the per-call native overhead amortized
without giving up bounded memory.

``shard_files(rank, world)`` (on file-backed sources) returns a copy that
reads only ``paths[rank::world]`` — the Spark-partition-style I/O split.
Record counts per rank are then ragged; see ``ingest.pipeline`` for the
batch-count equalization contract.
"""

from __future__ import annotations

from typing import Callable, Iterator, Sequence

import numpy as np


def _as_paths(paths: str | Sequence[str]) -> list[str]:
    out = [paths] if isinstance(paths, str) else list(paths)
    if not out:
        raise ValueError("need at least one path")
    return out


class ArraySource:
    """In-memory rows as a record stream (the ``ArrayDataset`` analogue):
    record i is ``tuple(a[i] for a in arrays)``. The bench/test workhorse
    and the adapter for datasets that already fit in memory."""

    def __init__(self, *arrays: np.ndarray, name: str = "array") -> None:
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError(f"length mismatch: {[len(a) for a in arrays]}")
        self.arrays = tuple(np.asarray(a) for a in arrays)
        self.name = name

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __iter__(self) -> Iterator[tuple]:
        for i in range(len(self)):
            yield tuple(a[i] for a in self.arrays)


class PairSource:
    """Ragged (src_ids, trg_ids) pairs — the online-packing input. Pairs
    are lists of ints (e.g. ``TextPipeline.ragged`` output)."""

    def __init__(self, pairs: Sequence[tuple], name: str = "pairs") -> None:
        self.pairs = list(pairs)
        self.name = name

    def __len__(self) -> int:
        return len(self.pairs)

    def __iter__(self) -> Iterator[tuple]:
        for s, t in self.pairs:
            yield (list(s), list(t))


class LibsvmStreamSource:
    """Stream ``(features float32 [num_features], label int64)`` records
    from libsvm files, parsing ``chunk_lines`` lines at a time through the
    native parser (``native/libsvm_parser.cpp``) when built, else the
    pure-Python fallback — bit-identical outputs (pinned by
    ``tests/test_native.py``).

    ``num_features`` is required: a streaming reader cannot discover the
    global max index without a full pass, and the static batch shape must
    be known up front (Spark's ``numFeatures`` option has the same role).
    A chunk containing an index above it raises, like ``read_libsvm``.
    """

    def __init__(
        self,
        paths: str | Sequence[str],
        *,
        num_features: int,
        chunk_lines: int = 1024,
        use_native: bool | None = None,
        name: str = "libsvm",
    ) -> None:
        if num_features < 1:
            raise ValueError(f"num_features must be >= 1, got {num_features}")
        if chunk_lines < 1:
            raise ValueError(f"chunk_lines must be >= 1, got {chunk_lines}")
        self.paths = _as_paths(paths)
        self.num_features = num_features
        self.chunk_lines = chunk_lines
        self.use_native = use_native
        self.name = name

    def shard_files(self, rank: int, world: int) -> "LibsvmStreamSource":
        if world > len(self.paths):
            raise ValueError(
                f"cannot file-shard {len(self.paths)} file(s) over "
                f"{world} ranks (some ranks would read nothing)"
            )
        return LibsvmStreamSource(
            self.paths[rank::world],
            num_features=self.num_features,
            chunk_lines=self.chunk_lines,
            use_native=self.use_native,
            name=self.name,
        )

    def _parse_chunk(
        self, text: str, path: str, line_offset: int
    ) -> tuple[np.ndarray, np.ndarray]:
        parse_native = None
        if self.use_native is None or self.use_native:
            try:
                from machine_learning_apache_spark_tpu.native import (
                    libsvm_native,
                )

                parse_native = libsvm_native.parse_text
            except (ImportError, OSError):
                if self.use_native:
                    raise
        try:
            if parse_native is not None:
                features, labels = parse_native(text)
            else:
                from machine_learning_apache_spark_tpu.data.libsvm import (
                    _parse_python,
                )

                features, labels, _ = _parse_python(text)
        except ValueError as e:
            # Parser line numbers are chunk-relative; re-anchor to the file.
            raise ValueError(
                f"{path}: lines {line_offset + 1}.."
                f"{line_offset + len(text.splitlines())}: {e}"
            ) from e
        if features.shape[1] > self.num_features:
            raise ValueError(
                f"{path}: feature index {features.shape[1]} > "
                f"num_features={self.num_features}"
            )
        if features.shape[1] < self.num_features:
            pad = np.zeros(
                (features.shape[0], self.num_features - features.shape[1]),
                np.float32,
            )
            features = np.concatenate([features, pad], axis=1)
        return features.astype(np.float32), labels.astype(np.int64)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.int64]]:
        for path in self.paths:
            with open(path) as f:
                lineno = 0
                while True:
                    lines = []
                    for line in f:
                        lines.append(line)
                        if len(lines) >= self.chunk_lines:
                            break
                    if not lines:
                        break
                    features, labels = self._parse_chunk(
                        "".join(lines), path, lineno
                    )
                    lineno += len(lines)
                    for i in range(len(labels)):
                        yield (features[i], labels[i])


class TextLineSource:
    """Stream stripped, non-empty lines from text files. Pair with a
    ``transform`` on the pipeline (or ``EncodedTextSource`` below) to turn
    lines into model inputs."""

    def __init__(self, paths: str | Sequence[str], name: str = "text") -> None:
        self.paths = _as_paths(paths)
        self.name = name

    def shard_files(self, rank: int, world: int) -> "TextLineSource":
        if world > len(self.paths):
            raise ValueError(
                f"cannot file-shard {len(self.paths)} file(s) over "
                f"{world} ranks (some ranks would read nothing)"
            )
        return TextLineSource(self.paths[rank::world], name=self.name)

    def __iter__(self) -> Iterator[str]:
        for path in self.paths:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        yield line


class EncodedTextSource:
    """Stream ``(token_ids int32 [fixed_len], label int64)`` records from
    raw texts, encoding ``chunk`` texts at a time through a fitted
    ``data.text.TextPipeline`` — which takes the native ``text_encode.cpp``
    fast path when built. The streaming counterpart of calling the
    pipeline on the whole corpus at once."""

    def __init__(
        self,
        texts: Sequence[str],
        labels: Sequence[int] | np.ndarray,
        pipe,
        *,
        chunk: int = 256,
        name: str = "encoded_text",
    ) -> None:
        if len(texts) != len(labels):
            raise ValueError(
                f"texts/labels length mismatch: {len(texts)} vs {len(labels)}"
            )
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.texts = list(texts)
        self.labels = np.asarray(labels, dtype=np.int64)
        self.pipe = pipe
        self.chunk = chunk
        self.name = name

    def __len__(self) -> int:
        return len(self.texts)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.int64]]:
        for start in range(0, len(self.texts), self.chunk):
            batch = self.texts[start : start + self.chunk]
            ids = self.pipe(batch)
            for i in range(len(batch)):
                yield (ids[i], self.labels[start + i])


class CallableSource:
    """Adapter for an arbitrary restartable record stream: ``factory()``
    is called once per pass and must return a fresh iterator."""

    def __init__(self, factory: Callable[[], Iterator], name: str = "fn"):
        self.factory = factory
        self.name = name

    def __iter__(self) -> Iterator:
        return iter(self.factory())
