"""Online sequence packing — ``data.packing`` semantics, one pair at a time.

``data.packing.pack_translation_pairs`` packs a whole corpus in one call
(next-fit in corpus order). The streaming pipeline cannot afford the
whole corpus; this module re-expresses the SAME next-fit policy as an
incremental fold so the loader thread can pack as records arrive. The
parity contract — feeding a corpus through ``OnlinePacker`` yields
byte-identical rows, in order, to the one-shot call — is pinned by
``tests/test_ingest.py``.

A packed *row* is the 6-tuple ``(src, src_segments, src_positions, trg,
trg_segments, trg_positions)`` of int32 ``[length]`` vectors — one row of
the ``PackedPairs`` arrays; the pipeline stacks ``batch_size`` of them
into the static-shape batch the packed-transformer loss consumes.
"""

from __future__ import annotations

import numpy as np


class OnlinePacker:
    """Incremental next-fit packer over (src_ids, trg_ids) pairs.

    ``add(src, trg)`` returns a completed packed row whenever the open row
    flushes (the incoming pair did not fit), else None; ``flush()``
    returns the final open row (or None). Same drop rule as the one-shot
    packer: pairs with no attendable src or <2 trg tokens after truncation
    are dropped and counted in ``dropped_pairs``.
    """

    def __init__(
        self,
        *,
        src_len: int,
        trg_len: int,
        pad_id: int = 0,
        max_segments: int | None = None,
    ) -> None:
        if src_len < 1 or trg_len < 2:
            # trg needs >= 2 so teacher forcing has a scored position —
            # identical guard to pack_translation_pairs.
            raise ValueError(
                f"row budgets too small: src {src_len}, trg {trg_len}"
            )
        self.src_len = src_len
        self.trg_len = trg_len
        self.pad_id = pad_id
        self.max_segments = max_segments
        self._open_src: list[list[int]] = []
        self._open_trg: list[list[int]] = []
        self._used_s = 0
        self._used_t = 0
        self.pair_count = 0
        self.dropped_pairs = 0
        self.rows_emitted = 0
        self.packed_tokens = 0

    def _materialize(self) -> tuple[np.ndarray, ...]:
        row: list[np.ndarray] = []
        for ids_lists, length in (
            (self._open_src, self.src_len),
            (self._open_trg, self.trg_len),
        ):
            arr = np.full(length, self.pad_id, dtype=np.int32)
            seg = np.zeros(length, dtype=np.int32)
            pos = np.zeros(length, dtype=np.int32)
            cursor = 0
            for j, ids in enumerate(ids_lists, start=1):
                arr[cursor : cursor + len(ids)] = ids
                seg[cursor : cursor + len(ids)] = j
                pos[cursor : cursor + len(ids)] = np.arange(len(ids))
                cursor += len(ids)
            self.packed_tokens += cursor
            row += [arr, seg, pos]
        self.rows_emitted += 1
        return tuple(row)

    def _flush_open(self) -> tuple[np.ndarray, ...] | None:
        if not self._open_src:
            return None
        row = self._materialize()
        self._open_src, self._open_trg = [], []
        self._used_s = self._used_t = 0
        return row

    def add(self, src, trg) -> tuple[np.ndarray, ...] | None:
        s = list(src)[: self.src_len]
        t = list(trg)[: self.trg_len]
        if not s or len(t) < 2:
            self.dropped_pairs += 1
            return None
        full = (
            self._used_s + len(s) > self.src_len
            or self._used_t + len(t) > self.trg_len
            or (
                self.max_segments is not None
                and len(self._open_src) >= self.max_segments
            )
        )
        out = self._flush_open() if full else None
        self._open_src.append(s)
        self._open_trg.append(t)
        self._used_s += len(s)
        self._used_t += len(t)
        self.pair_count += 1
        return out

    def flush(self) -> tuple[np.ndarray, ...] | None:
        """End-of-stream: materialize and return the open row, if any."""
        return self._flush_open()

    @property
    def token_efficiency(self) -> float:
        """Non-pad fraction of the emitted token grid (matches the
        one-shot packer's definition over the same rows)."""
        grid = self.rows_emitted * (self.src_len + self.trg_len)
        return self.packed_tokens / grid if grid else 0.0
