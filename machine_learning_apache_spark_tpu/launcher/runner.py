"""Per-worker entry point for the Distributor gang.

Keep module-scope imports stdlib-only: this module is imported in every
spawned worker *before* the JAX platform choice is settled, and the heavy
framework import happens only after the rendezvous env is in place.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import threading
import time
import traceback


def _start_heartbeat(
    path: str, interval: float, rank: int = 0, world: int | None = None
) -> threading.Thread:
    """Rewrite ``path`` every ``interval`` seconds from a daemon thread —
    the liveness signal ``launcher.monitor.GangMonitor`` watches (by
    mtime) and, since each beat is now a JSON payload (rank, pid, phase,
    step, http_port), also the gang-status signal ``tools/gang_status.py``
    reads for content. Atomic tmp+replace so a reader never sees a torn
    beat; the mtime contract is unchanged, so old monitors keep working.

    Started before the heavy framework imports so a wedged import counts
    as the stall it is only after the full ``heartbeat_timeout``, not as
    instant death. The beat loop holds no lock and touches nothing
    shared, so it keeps beating through compiles and collectives (which
    release the GIL); it stops only when the process truly wedges — or
    when a ``stall`` fault suspends it to simulate exactly that.
    """

    def suspended() -> bool:
        # sys.modules peek instead of an import: the faults module lives
        # behind package __init__s that drag in jax, and this thread must
        # stay stdlib-only. If user code never imported it, no stall
        # fault can have fired.
        mod = sys.modules.get("machine_learning_apache_spark_tpu.utils.faults")
        return bool(mod is not None and mod.heartbeats_suspended())

    def beacon() -> dict:
        # Same peek discipline for the telemetry beacon (phase, step,
        # http_port): events.py is stdlib-only but sits under the heavy
        # package __init__, so this thread must not import it. Before the
        # worker's framework import, the module is absent and the beat
        # carries liveness only.
        mod = sys.modules.get(
            "machine_learning_apache_spark_tpu.telemetry.events"
        )
        if mod is None:
            return {}
        try:
            return mod.beacon()
        except Exception:
            return {}

    def beat() -> None:
        while True:
            if not suspended():
                b = beacon()
                payload = {
                    "rank": rank,
                    "pid": os.getpid(),
                    "wall": round(time.time(), 3),
                    "phase": b.get("phase"),
                    "step": b.get("step"),
                    "http_port": b.get("http_port"),
                    # World size as this worker sees it — after an
                    # elastic shrink the scrape tables show the gang's
                    # CURRENT world, not the launch-time one.
                    "world": world,
                }
                tmp = f"{path}.tmp.{os.getpid()}"
                try:
                    with open(tmp, "w") as f:
                        json.dump(payload, f)
                        f.write("\n")
                    os.replace(tmp, path)
                except OSError:
                    pass  # workdir tearing down — the gang is over anyway
            time.sleep(interval)

    t = threading.Thread(target=beat, name="mlspark-heartbeat", daemon=True)
    t.start()
    return t


def _install_sigterm_flight(tm, rank: int) -> None:
    """On the gang teardown's SIGTERM, dump this worker's flight recorder
    and export its rank timeline before dying with the default disposition
    — the innocent ranks of a failed gang ship their last events too.
    Best-effort: a worker without a main-thread signal context keeps the
    default handler."""
    import signal

    def handler(signum, frame):  # noqa: ARG001
        try:
            tm.dump_flight("launcher.sigterm")
            tdir = tm.telemetry_dir()
            if tdir and tm.enabled():
                tm.write_rank_file(tdir, rank=rank)
        except Exception:
            pass
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, handler)
    except (ValueError, OSError):  # non-main thread / exotic host
        pass


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--fn", required=True, help="module:qualname")
    parser.add_argument("--args-file", default=None)
    parser.add_argument("--result-file", default=None)
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    ns = parser.parse_args(argv)

    # CLI rendezvous flags (multi-host path) take precedence over env.
    # This whole pre-import section keeps direct os.environ access: the
    # heartbeat must start BEFORE any framework import (import time is
    # covered by liveness), and utils.env — like everything under the
    # package — pulls the heavy package __init__. The names are still
    # registered; only the accessor differs here.
    if ns.coordinator:
        os.environ["MLSPARK_COORDINATOR"] = ns.coordinator  # mlspark-lint: ok env-direct-read -- pre-import section, see above
    if ns.num_processes is not None:
        os.environ["MLSPARK_NUM_PROCESSES"] = str(ns.num_processes)  # mlspark-lint: ok env-direct-read -- pre-import section
    if ns.process_id is not None:
        os.environ["MLSPARK_PROCESS_ID"] = str(ns.process_id)  # mlspark-lint: ok env-direct-read -- pre-import section

    rank = int(os.environ.get("MLSPARK_PROCESS_ID", "0"))  # mlspark-lint: ok env-direct-read -- pre-import section

    # Liveness beacon for the driver's GangMonitor — started before the
    # framework imports so rendezvous/import time is covered too.
    heartbeat_file = os.environ.get("MLSPARK_HEARTBEAT_FILE")  # mlspark-lint: ok env-direct-read -- pre-import section
    if heartbeat_file:
        world_raw = os.environ.get("MLSPARK_NUM_PROCESSES")  # mlspark-lint: ok env-direct-read -- pre-import section
        _start_heartbeat(
            heartbeat_file,
            float(os.environ.get("MLSPARK_HEARTBEAT_INTERVAL", "1.0")),  # mlspark-lint: ok env-direct-read -- pre-import section
            rank=rank,
            world=int(world_raw) if world_raw else None,
        )

    args, kwargs = ((), {})
    if ns.args_file:
        with open(ns.args_file, "rb") as f:
            args, kwargs = pickle.load(f)

    result: dict = {"rank": rank, "value": None, "error": None}
    code = 0
    tm = None  # telemetry module, bound after platform config
    try:
        # Platform choice must go through the config API: the hosting image's
        # sitecustomize registers the axon TPU plugin in every process and
        # the JAX_PLATFORMS env var alone does not stick (see
        # tests/conftest.py). Must happen before any backend/device touch.
        platform = os.environ.get("MLSPARK_PLATFORM")  # mlspark-lint: ok env-direct-read -- read must precede the first package import
        if platform:
            import jax

            jax.config.update("jax_platforms", platform)

        # Telemetry comes up only now: importing it pulls the package
        # __init__ (heavy), which must not precede the platform override.
        from machine_learning_apache_spark_tpu import telemetry as tm

        _install_sigterm_flight(tm, rank)

        # Live observability plane: start this rank's HTTP server (no-op
        # with zero threads unless MLSPARK_TELEMETRY_HTTP is set) and seed
        # the beacon so the very next heartbeat carries phase + http_port.
        tm.beacon_update(phase="startup")
        tm.start_http_server(rank=rank)

        # Record the gang's data-parallel update contract on this rank's
        # timeline (MLSPARK_DP_MODE / bucket / comms-dtype — set by
        # Distributor(dp_mode=...) or inherited; consumed by fit() via
        # parallel.zero.resolve_dp_mode). The merged telemetry report's
        # comms section reads next to this breadcrumb.
        from machine_learning_apache_spark_tpu.utils import env as envcfg

        dp_mode = envcfg.raw("MLSPARK_DP_MODE")
        if dp_mode:
            tm.annotate(
                "launcher.dp_mode",
                mode=dp_mode,
                bucket_bytes=envcfg.raw("MLSPARK_ZERO1_BUCKET_BYTES"),
                comms_dtype=envcfg.raw("MLSPARK_COMMS_DTYPE"),
            )

        # Rendezvous before user code touches devices — the
        # dist.init_process_group analogue (distributed_cnn.py:152).
        from machine_learning_apache_spark_tpu.launcher.coordinator import (
            initialize_from_env,
        )

        initialize_from_env()

        from machine_learning_apache_spark_tpu.launcher.distributor import (
            resolve_fn,
        )

        with tm.span(
            "launcher.worker", fn=ns.fn, rank=rank,
            attempt=envcfg.get_int("MLSPARK_GANG_ATTEMPT"),
        ):
            result["value"] = resolve_fn(ns.fn)(*args, **kwargs)
    except BaseException:  # noqa: BLE001 - worker must report, not die silently
        result["error"] = traceback.format_exc()
        code = 1
        if tm is not None:
            tm.dump_flight("launcher.worker_exception")
    finally:
        # Per-rank timeline export (telemetry_rank<k>.jsonl, next to the
        # heartbeat files unless MLSPARK_TELEMETRY_DIR points elsewhere) —
        # the input to telemetry.aggregate / tools/telemetry_report.py.
        if tm is not None and tm.enabled():
            tdir = tm.telemetry_dir()
            if tdir:
                try:
                    tm.write_rank_file(tdir, rank=rank)
                except Exception:
                    traceback.print_exc()
        if ns.result_file:
            from machine_learning_apache_spark_tpu.launcher.distributor import (
                WorkerResult,
            )

            payload = WorkerResult(**result)
            if code == 0 and rank != 0:
                # Only rank 0's value crosses back (distributor.run contract,
                # distributed_cnn.py:231); other ranks report success only.
                payload.value = None
            try:
                with open(ns.result_file, "wb") as f:
                    pickle.dump(payload, f)
            except Exception:
                # Unpicklable return value: replace the (possibly truncated)
                # file with an error result so the driver reports this rank's
                # real failure rather than an unpickling artifact.
                traceback.print_exc()
                code = code or 1
                payload = WorkerResult(
                    rank=rank,
                    error=f"rank {rank} result not picklable:\n{traceback.format_exc()}",
                )
                try:
                    with open(ns.result_file, "wb") as f:
                        pickle.dump(payload, f)
                except Exception:
                    traceback.print_exc()
    return code


if __name__ == "__main__":
    sys.exit(main())
