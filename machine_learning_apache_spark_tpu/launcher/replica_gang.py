"""Replica-gang launch mode — N independent workers, per-rank restart.

The ``Distributor`` implements Spark-barrier semantics on purpose: one
dead rank fails the gang, the gang retries whole. That is right for
training (a collective missing one participant deadlocks) and exactly
wrong for a serving fleet, where the whole point of running N replicas
is that losing one costs one replica's in-flight work and *nothing
else*. ``ReplicaGang`` is the launcher's second launch mode for that
shape:

- Each rank is a standalone ``launcher.runner`` subprocess (same entry
  point, same heartbeat/telemetry/platform plumbing) with **no
  rendezvous env** — ``initialize_from_env`` no-ops, so replicas never
  form a collective and one dying cannot wedge the rest.
- A supervisor thread watches exits and heartbeat staleness **per
  rank** and restarts only the dead rank, with exponential backoff and
  a per-rank restart budget. A restarted replica re-binds an ephemeral
  port and overwrites its sidecars; discovery (``fleet/scrape.py``)
  follows it there.
- ``kill_rank`` is the fault-drill hook: SIGKILL one replica's process
  group and let supervision prove the recovery story.

Process-group hygiene matches the Distributor: every worker is a
session leader, registered in the module-level stray-gang registry so
the atexit/conftest sweeps reap leftovers from a crashed driver.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any

from machine_learning_apache_spark_tpu.launcher.distributor import (
    _register_gang,
    _unregister_gang,
    fn_reference,
)
from machine_learning_apache_spark_tpu.launcher.monitor import (
    _signal_proc,
    terminate_gang,
)
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: Env vars that would make a replica try to rendezvous — scrubbed from
#: every spawn (replicas are world-size-1 by construction).
_RENDEZVOUS_ENV = (
    "MLSPARK_COORDINATOR", "MASTER_ADDR", "MASTER_PORT",
    "WORLD_SIZE", "RANK", "MLSPARK_NUM_PROCESSES",
)


class ReplicaGang:
    """Spawn and supervise ``num_replicas`` independent serving workers.

    ``fn`` is run by importable reference in every rank (the
    ``fleet.replica.serve_replica`` wrapper, usually). The gang does not
    block: ``start()`` returns once every rank is spawned; the replicas
    announce themselves through their own sidecars. ``stop()`` drops the
    ``fleet_stop`` marker for a clean drain, then escalates.
    """

    def __init__(
        self,
        fn,
        *args: Any,
        num_replicas: int = 2,
        workdir: str | None = None,
        platform: str | None = None,
        env: dict[str, str] | None = None,
        telemetry_http: int | None = 0,
        heartbeat_interval: float = 0.5,
        heartbeat_timeout: float | None = None,
        max_restarts_per_rank: int = 2,
        backoff_base: float = 0.5,
        backoff_max: float = 10.0,
        term_grace: float = 5.0,
        **kwargs: Any,
    ):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}"
            )
        self.ref = fn_reference(fn)
        self.call_args = (args, kwargs)
        self.num_replicas = num_replicas
        self.workdir = workdir or tempfile.mkdtemp(prefix="mlspark_fleet_")
        self.platform = platform
        self.extra_env = env or {}
        self.telemetry_http = telemetry_http
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.max_restarts_per_rank = max_restarts_per_rank
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.term_grace = term_grace
        self._lock = threading.Lock()
        self._procs: dict[int, subprocess.Popen] = {}
        self._restart_at: dict[int, float] = {}  # rank -> not-before time
        self.restarts: dict[int, int] = {r: 0 for r in range(num_replicas)}
        self.exhausted: set[int] = set()
        # Dynamic membership (the autoscaler's levers): a retiring rank
        # sits in ``_retiring`` (rank -> kill-backstop deadline) until its
        # process exits, then moves to ``retired`` after sidecar cleanup.
        self._retiring: dict[int, float] = {}
        self.retired: set[int] = set()
        self._stop = threading.Event()
        self._supervisor: threading.Thread | None = None
        os.makedirs(self.workdir, exist_ok=True)
        self._args_path = os.path.join(self.workdir, "fleet_args.pkl")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaGang":
        if self._supervisor is not None:
            raise RuntimeError("replica gang already started")
        import pickle

        with open(self._args_path, "wb") as f:
            pickle.dump(self.call_args, f)
        stop_marker = os.path.join(self.workdir, "fleet_stop")
        if os.path.exists(stop_marker):
            os.unlink(stop_marker)  # stale marker from a previous gang
        self._stop.clear()
        for rank in range(self.num_replicas):
            self._spawn(rank)
        self._supervisor = threading.Thread(
            target=self._supervise, name="replica-gang-supervisor",
            daemon=True,
        )
        self._supervisor.start()
        log.info(
            "replica gang up: %d rank(s) in %s",
            self.num_replicas, self.workdir,
        )
        return self

    def stop(self, *, drain_s: float = 15.0) -> None:
        """Graceful drain: drop the stop marker, give replicas
        ``drain_s`` to exit on their own, then SIGTERM→SIGKILL."""
        self._stop.set()
        try:
            with open(os.path.join(self.workdir, "fleet_stop"), "w") as f:
                f.write("stop\n")
        except OSError:
            pass
        t = self._supervisor
        if t is not None:
            t.join(5.0)
        self._supervisor = None
        with self._lock:
            procs = list(self._procs.values())
        deadline = time.monotonic() + drain_s
        for p in procs:
            remaining = max(0.05, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                pass
        terminate_gang(procs, grace=self.term_grace)
        _unregister_gang(procs)
        with self._lock:
            self._procs.clear()

    def __enter__(self) -> "ReplicaGang":
        if self._supervisor is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- spawn/supervise -----------------------------------------------------
    def _spawn(self, rank: int) -> None:
        # A stale drain marker for this rank id would make the fresh
        # replica retire itself on its first poll — scrub it first.
        try:
            os.unlink(os.path.join(self.workdir, f"fleet_drain_rank{rank}"))
        except OSError:
            pass
        heartbeat_path = os.path.join(self.workdir, f"heartbeat_{rank}")
        env = dict(os.environ)
        for name in _RENDEZVOUS_ENV:
            env.pop(name, None)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # Same scrub as the Distributor: a replica is one device.
            kept = " ".join(
                f for f in flags.split()
                if "xla_force_host_platform_device_count" not in f
            )
            if kept:
                env["XLA_FLAGS"] = kept
            else:
                env.pop("XLA_FLAGS", None)
        env.update(self.extra_env)
        env.setdefault("MLSPARK_TELEMETRY_DIR", self.workdir)
        env.setdefault("MLSPARK_FLEET_DIR", self.workdir)
        env.setdefault("MLSPARK_FLEET_PORT", "0")
        env["MLSPARK_PROCESS_ID"] = str(rank)
        env["MLSPARK_GANG_ATTEMPT"] = str(self.restarts[rank])
        env["MLSPARK_HEARTBEAT_FILE"] = heartbeat_path
        env["MLSPARK_HEARTBEAT_INTERVAL"] = str(self.heartbeat_interval)
        if self.telemetry_http is not None:
            env["MLSPARK_TELEMETRY_HTTP"] = str(self.telemetry_http)
        if self.platform:
            env["JAX_PLATFORMS"] = self.platform
            env["MLSPARK_PLATFORM"] = self.platform
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        cmd = [
            sys.executable,
            "-m",
            "machine_learning_apache_spark_tpu.launcher.runner",
            "--fn", self.ref,
            "--args-file", self._args_path,
            "--result-file",
            os.path.join(self.workdir, f"fleet_result_{rank}.pkl"),
        ]
        proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        with self._lock:
            self._procs[rank] = proc
        _register_gang([proc])

    def _supervise(self) -> None:
        """Per-rank detection + restart. First failure of rank k costs
        rank k a restart, nothing else — the anti-barrier."""
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                ranks = dict(self._procs)
            for rank, proc in ranks.items():
                dead = proc.poll() is not None
                backstop = self._retiring.get(rank)
                if backstop is not None:
                    # Deliberate retirement: never restart. Finalize on
                    # exit, or SIGKILL past the drain-deadline backstop
                    # (a wedged replica must not block the scale-down).
                    if dead:
                        self._finalize_retirement(rank, proc)
                    elif now >= backstop:
                        log.warning(
                            "replica %d missed its drain deadline; "
                            "killing to finish retirement", rank,
                        )
                        _signal_proc(proc, signal.SIGKILL)
                        try:
                            proc.wait(timeout=10.0)
                        except subprocess.TimeoutExpired:
                            pass
                        self._finalize_retirement(rank, proc)
                    continue
                stalled = (
                    not dead
                    and self.heartbeat_timeout is not None
                    and self._heartbeat_age(rank, now) > self.heartbeat_timeout
                )
                if not (dead or stalled):
                    continue
                if stalled:
                    log.warning(
                        "replica %d stalled (heartbeat silent > %.1fs); "
                        "killing for restart", rank, self.heartbeat_timeout,
                    )
                    _signal_proc(proc, signal.SIGKILL)
                    proc.wait(timeout=10.0)
                _unregister_gang([proc])
                if self.restarts[rank] >= self.max_restarts_per_rank:
                    if rank not in self.exhausted:
                        self.exhausted.add(rank)
                        with self._lock:
                            self._procs.pop(rank, None)
                        log.error(
                            "replica %d exhausted its restart budget "
                            "(%d); leaving it down",
                            rank, self.max_restarts_per_rank,
                        )
                    continue
                not_before = self._restart_at.get(rank, 0.0)
                if now < not_before:
                    continue
                self.restarts[rank] += 1
                delay = min(
                    self.backoff_max,
                    self.backoff_base * (2 ** (self.restarts[rank] - 1)),
                )
                self._restart_at[rank] = now + delay
                log.warning(
                    "replica %d down (exit=%s); restart %d/%d",
                    rank, proc.returncode, self.restarts[rank],
                    self.max_restarts_per_rank,
                )
                self._spawn(rank)
            self._stop.wait(0.2)

    def _heartbeat_age(self, rank: int, now: float) -> float:
        path = os.path.join(self.workdir, f"heartbeat_{rank}")
        try:
            return max(0.0, time.time() - os.stat(path).st_mtime)
        except OSError:
            # No beat yet: age since spawn is unknowable here; treat as
            # young — exit detection covers a worker that died pre-beat.
            return 0.0

    # -- dynamic membership (the autoscaler's levers) ------------------------
    def add_rank(self) -> int:
        """Scale up by one: spawn a fresh replica on the lowest free rank
        id. A reused id (previously retired or exhausted) starts clean —
        restart budget reset, stale sidecars/markers scrubbed — so an old
        rank's history can't haunt its successor."""
        with self._lock:
            taken = set(self._procs) | set(self._retiring)
            rank = 0
            while rank in taken:
                rank += 1
        self.retired.discard(rank)
        self.exhausted.discard(rank)
        self.restarts[rank] = 0
        self._restart_at.pop(rank, None)
        self._cleanup_rank_files(rank)
        self._spawn(rank)
        log.info("replica %d added (scale-up)", rank)
        return rank

    def retire_rank(
        self, rank: int, *, drain: bool = True, deadline_s: float = 30.0
    ) -> bool:
        """Scale down by one: mark ``rank`` draining (marker file → the
        replica 503s new work, finishes in-flight, exits) and hand it to
        the supervisor for finalization. ``drain=False`` kills it
        outright. Returns False if the rank isn't live."""
        with self._lock:
            proc = self._procs.get(rank)
            if proc is None or rank in self._retiring:
                return False
            # Backstop is the replica's own deadline plus slack for its
            # exit path; the supervisor SIGKILLs past it.
            self._retiring[rank] = (
                time.monotonic() + (deadline_s if drain else 0.0) + 10.0
            )
        if not drain or proc.poll() is not None:
            _signal_proc(proc, signal.SIGKILL)
            return True
        marker = os.path.join(self.workdir, f"fleet_drain_rank{rank}")
        try:
            tmp = f"{marker}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"deadline": time.time() + deadline_s,
                           "rank": rank}, f)
                f.write("\n")
            os.replace(tmp, marker)
        except OSError:
            # Can't signal the drain — kill rather than leak the rank.
            _signal_proc(proc, signal.SIGKILL)
        log.info(
            "replica %d retiring (drain deadline %.1fs)", rank, deadline_s
        )
        return True

    def reap_rank(self, rank: int) -> bool:
        """Absorb a permanently-dead rank (restart budget exhausted) as an
        observed scale-down: scrub its sidecars so discovery drops it and
        the router purges its routing state. The rank id becomes free for
        reuse by a later ``add_rank``. Returns False unless the rank is
        actually down for good."""
        with self._lock:
            if rank in self._procs or rank in self._retiring:
                return False
        if rank not in self.exhausted and rank not in self.retired:
            return False
        self.retired.add(rank)
        self._cleanup_rank_files(rank)
        log.info("replica %d reaped (observed scale-down)", rank)
        return True

    def _finalize_retirement(self, rank: int, proc) -> None:
        _unregister_gang([proc])
        with self._lock:
            self._procs.pop(rank, None)
            self._retiring.pop(rank, None)
        self.retired.add(rank)
        self._cleanup_rank_files(rank)
        log.info("replica %d retired (exit=%s)", rank, proc.returncode)

    def _cleanup_rank_files(self, rank: int) -> None:
        """Remove one rank's discovery/heartbeat droppings so a retired
        rank vanishes from the scrape plane and a reused id starts
        clean."""
        for name in (
            f"fleet_rank{rank}.json",
            f"http_rank{rank}.json",
            f"heartbeat_{rank}",
            f"fleet_drain_rank{rank}",
        ):
            try:
                os.unlink(os.path.join(self.workdir, name))
            except OSError:
                pass

    # -- drill hooks / introspection -----------------------------------------
    def kill_rank(self, rank: int) -> bool:
        """SIGKILL one replica's process group (the fault-drill lever).
        Supervision notices and restarts it within a poll interval."""
        with self._lock:
            proc = self._procs.get(rank)
        if proc is None or proc.poll() is not None:
            return False
        _signal_proc(proc, signal.SIGKILL)
        return True

    def alive(self) -> dict[int, bool]:
        with self._lock:
            return {
                rank: proc.poll() is None
                for rank, proc in sorted(self._procs.items())
            }

    def live_ranks(self) -> list[int]:
        """Ranks with a running process that are *not* mid-retirement —
        the autoscaler's notion of current fleet size."""
        with self._lock:
            return sorted(
                rank for rank, proc in self._procs.items()
                if proc.poll() is None and rank not in self._retiring
            )

    def status(self) -> dict:
        return {
            "num_replicas": self.num_replicas,
            "alive": self.alive(),
            "restarts": dict(self.restarts),
            "exhausted": sorted(self.exhausted),
            "retiring": sorted(self._retiring),
            "retired": sorted(self.retired),
            "workdir": self.workdir,
        }
