"""Gang health monitoring — failure *detection* for the Distributor.

The Spark barrier scheduler's contract (SURVEY.md §5) is all-or-nothing:
one dead task fails the stage, the stage retries whole. The seed
reproduction had the teardown half of that contract but only one
detector (exit codes, polled inline) and one escalation level (SIGKILL).
This module completes it:

- ``GangMonitor`` — a daemon thread watching every worker for the three
  ways a gang member dies: **exit** (nonzero return code), **stalled
  heartbeat** (the worker's heartbeat file — touched by
  ``runner``'s beat thread — goes stale past ``heartbeat_timeout``; the
  hung-not-dead case exit codes can never catch), and **deadline** (the
  whole gang overrunning its budget). First detection wins, is recorded
  as a structured ``GangFailure``, and triggers teardown.
- ``terminate_gang`` — SIGTERM first (workers get to flush result files
  and die cleanly), SIGKILL whatever is still alive after the grace
  period. Workers are spawned as session leaders, so signals go to the
  whole process group — a worker's own children can't orphan past the
  gang.

The monitor never raises; it records. The Distributor reads
``monitor.failure`` after joining and turns it into the exception, with
the result files' tracebacks attached.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


def read_heartbeat(path: str) -> dict:
    """Parse one heartbeat file's JSON payload (rank, pid, phase, step,
    http_port — written by ``runner._start_heartbeat``). Returns ``{}``
    for legacy empty-touch beats, torn writes, or unreadable files: the
    payload is enrichment, the mtime is the liveness contract."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return {}
    return payload if isinstance(payload, dict) else {}


class GangFailure(RuntimeError):
    """A gang attempt failed. Structured fields over string parsing:

    - ``rank`` — the first rank detected failing (None for whole-gang
      causes like deadline expiry);
    - ``cause`` — ``"exit"`` | ``"heartbeat"`` | ``"deadline"``;
    - ``attempt`` — 0-based gang attempt this failure ended;
    - ``exit_code`` — the failing rank's exit code (exit cause only);
    - ``permanent`` — the rank exhausted its per-rank restart budget
      (``Distributor``'s elastic policy judged it permanently lost and
      either could not shrink further or elastic resume was disabled).
    """

    def __init__(
        self,
        message: str,
        *,
        rank: int | None = None,
        cause: str = "exit",
        attempt: int = 0,
        exit_code: int | None = None,
        permanent: bool = False,
    ):
        super().__init__(message)
        self.rank = rank
        self.cause = cause
        self.attempt = attempt
        self.exit_code = exit_code
        self.permanent = permanent


def _signal_proc(proc: subprocess.Popen, sig: int) -> None:
    """Deliver ``sig`` to the worker's whole process group (it was
    spawned as a session leader), falling back to the single pid."""
    try:
        os.killpg(proc.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass


def terminate_gang(
    procs: list[subprocess.Popen], *, grace: float = 5.0
) -> None:
    """Fail-fast teardown with escalation: SIGTERM every live worker,
    give the gang ``grace`` seconds to exit (enough to flush a result
    file), SIGKILL the rest, and reap everything."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        _signal_proc(p, signal.SIGTERM)
    deadline = time.monotonic() + grace
    for p in live:
        remaining = deadline - time.monotonic()
        try:
            p.wait(timeout=max(remaining, 0.01))
        except subprocess.TimeoutExpired:
            pass
    killed = 0
    for p in live:
        if p.poll() is None:
            _signal_proc(p, signal.SIGKILL)
            killed += 1
    for p in procs:
        try:
            p.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # pragma: no cover - kernel refuses
            log.warning("worker pid %d survived SIGKILL reap window", p.pid)
    if live:
        log.info(
            "gang teardown: %d SIGTERMed, %d escalated to SIGKILL",
            len(live), killed,
        )


class GangMonitor(threading.Thread):
    """Watch a spawned gang until it finishes or a failure is detected.

    One monitor per gang attempt. ``join()`` it, then read ``failure``:
    None means every rank exited 0. On the first failure the monitor
    tears the remaining workers down itself (fail-fast: a gang missing a
    rank can only hang at the next collective — killing it immediately
    converts a silent stall into a structured, retryable error).

    Heartbeat accounting starts at spawn time: a worker that never
    produces its first beat (import wedged, rendezvous hung) is judged
    against the same ``heartbeat_timeout``, with mtimes older than the
    spawn (stale files from a previous attempt) ignored.
    """

    def __init__(
        self,
        procs: list[subprocess.Popen],
        heartbeat_paths: list[str] | None = None,
        *,
        timeout: float,
        heartbeat_timeout: float | None = None,
        grace: float = 5.0,
        poll_interval: float = 0.05,
    ):
        super().__init__(name="mlspark-gang-monitor", daemon=True)
        self.procs = procs
        self.heartbeat_paths = heartbeat_paths or []
        self.deadline = time.monotonic() + timeout
        self.timeout = timeout
        self.heartbeat_timeout = heartbeat_timeout or None
        self.grace = grace
        self.poll_interval = poll_interval
        self.failure: GangFailure | None = None
        self._spawned_at = time.monotonic()

    # -- detectors -----------------------------------------------------------
    def _last_beat(self, rank: int) -> float:
        """Monotonic-clock estimate of rank's most recent heartbeat."""
        try:
            mtime = os.stat(self.heartbeat_paths[rank]).st_mtime
        except (OSError, IndexError):
            return self._spawned_at
        # Heartbeat files carry wall-clock mtimes; convert the age to the
        # monotonic timeline the deadline math runs on.
        age = max(0.0, time.time() - mtime)
        return max(self._spawned_at, time.monotonic() - age)

    def _check_once(self, pending: set[int]) -> GangFailure | None:
        now = time.monotonic()
        for rank in sorted(pending):
            code = self.procs[rank].poll()
            if code is None:
                continue
            pending.discard(rank)
            if code != 0:
                return GangFailure(
                    f"rank {rank} exited with code {code}",
                    rank=rank, cause="exit", exit_code=code,
                )
        if self.heartbeat_timeout is not None:
            for rank in sorted(pending):
                silent = now - self._last_beat(rank)
                if silent > self.heartbeat_timeout:
                    # The stalled rank's last payload says what it was
                    # doing when it went quiet — the first question any
                    # postmortem asks.
                    last = (
                        read_heartbeat(self.heartbeat_paths[rank])
                        if rank < len(self.heartbeat_paths) else {}
                    )
                    where = ""
                    if last.get("phase") is not None:
                        where = f" (last phase {last['phase']!r}"
                        if last.get("step") is not None:
                            where += f", step {last['step']}"
                        where += ")"
                    return GangFailure(
                        f"rank {rank} missed heartbeats for {silent:.1f}s "
                        f"(timeout {self.heartbeat_timeout}s) — "
                        f"stalled{where}",
                        rank=rank, cause="heartbeat",
                    )
        if now > self.deadline:
            return GangFailure(
                f"gang did not finish within {self.timeout}s",
                cause="deadline",
            )
        return None

    def run(self) -> None:
        pending = set(range(len(self.procs)))
        while pending:
            failure = self._check_once(pending)
            if failure is not None:
                self.failure = failure
                log.warning("gang failure detected: %s", failure)
                telemetry.annotate(
                    "launcher.gang_failure",
                    rank=failure.rank, cause=failure.cause,
                    exit_code=failure.exit_code,
                )
                # Driver-side flight dump (flight_driver.json): what the
                # driver observed around the failure. Falls back to the
                # heartbeat dir (the gang workdir) when no telemetry dir is
                # configured — next to the files that triggered detection.
                tdir = telemetry.telemetry_dir() or (
                    os.path.dirname(self.heartbeat_paths[0])
                    if self.heartbeat_paths else None
                )
                telemetry.dump_flight(
                    f"launcher.gang_failure:{failure.cause}",
                    directory=tdir,
                    extra={"rank": failure.rank, "cause": failure.cause,
                           "exit_code": failure.exit_code},
                )
                telemetry.annotate("launcher.gang_teardown")
                terminate_gang(self.procs, grace=self.grace)
                return
            if pending:
                time.sleep(self.poll_interval)


__all__ = ["GangFailure", "GangMonitor", "read_heartbeat", "terminate_gang"]
