"""launcher — gang spawn + rendezvous (the TorchDistributor layer, C12)."""

from machine_learning_apache_spark_tpu.launcher.coordinator import (
    RendezvousSpec,
    initialize_from_env,
    shutdown,
)
from machine_learning_apache_spark_tpu.launcher.distributor import (
    Distributor,
    TorchDistributor,
    fn_reference,
    kill_stray_gangs,
)
from machine_learning_apache_spark_tpu.launcher.monitor import (
    GangFailure,
    GangMonitor,
    terminate_gang,
)
from machine_learning_apache_spark_tpu.launcher.replica_gang import (
    ReplicaGang,
)

__all__ = [
    "RendezvousSpec",
    "initialize_from_env",
    "shutdown",
    "Distributor",
    "TorchDistributor",
    "fn_reference",
    "GangFailure",
    "GangMonitor",
    "ReplicaGang",
    "kill_stray_gangs",
    "terminate_gang",
]
