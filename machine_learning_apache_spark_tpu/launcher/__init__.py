from machine_learning_apache_spark_tpu.launcher.coordinator import (
    RendezvousSpec,
    initialize_from_env,
)

__all__ = ["RendezvousSpec", "initialize_from_env"]
