"""Distributor — the TorchDistributor equivalent (reference C12).

The reference launches distributed training with
``TorchDistributor(num_processes=executors_n, local_mode=..., use_gpu=False)
.run(train_func)`` (``distributed_cnn.py:227-231``): Spark gang-schedules one
barrier task per process, sets the torch rendezvous env vars, pickles
``train_func`` with its module globals, and returns rank 0's result.

Design deltas (SURVEY.md §7 design stance):

- **Function by reference, not pickle-by-value**: the train function must be
  importable (``module:qualname`` or a module-level callable). This kills the
  reference's accidental re-execution of module-level downloads on every
  executor (quirk Q13) — each worker imports the module once, deliberately.
- **Rendezvous**: the launcher picks a free coordinator port and writes the
  ``{MLSPARK_COORDINATOR, NUM_PROCESSES, PROCESS_ID}`` env contract (plus the
  torch-style aliases) that ``launcher.coordinator`` maps onto
  ``jax.distributed.initialize`` (SURVEY.md §2.4).
- **Result**: rank 0's return value is actually returned (the reference's
  ``train_func``s return None yet assign the result — quirk Q7).
- **Gang failure semantics**: any worker dying kills the gang and raises —
  the Spark-barrier all-or-nothing behavior (SURVEY.md §5 failure detection).

``local_mode=True`` (the reference's bring-up path,
``distributed_multilayer_perceptron.py:179``) spawns all ranks on this host.
Multi-host mode emits the per-host command lines instead (control-plane
integration with an external scheduler; see ``commands_for_hosts``).
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Any, Callable, Sequence

from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def fn_reference(fn: Callable | str) -> str:
    """``module:qualname`` reference for an importable function."""
    if isinstance(fn, str):
        if ":" not in fn:
            raise ValueError(f"function reference must be 'module:qualname', got {fn!r}")
        return fn
    module = getattr(fn, "__module__", None)
    qualname = getattr(fn, "__qualname__", None)
    if not module or not qualname or "<" in qualname:
        raise ValueError(
            f"{fn!r} is not an importable module-level function; the launcher "
            "runs functions by reference (no closure pickling — SURVEY.md Q13)"
        )
    return f"{module}:{qualname}"


def resolve_fn(ref: str) -> Callable:
    """Import a ``module:qualname`` reference (shared by Distributor and the
    per-worker runner)."""
    import importlib

    module, _, qual = fn_reference(ref).partition(":")
    obj: Any = importlib.import_module(module)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


@dataclass
class WorkerResult:
    rank: int
    value: Any = None
    error: str | None = None


class Distributor:
    """``Distributor(num_processes=N, local_mode=True).run(train_fn, *args)``.

    ``use_gpu`` is accepted for API parity with TorchDistributor and ignored
    (the accelerator is whatever the JAX platform provides; the reference
    always passed ``use_gpu=False`` anyway, ``distributed_cnn.py:230``).
    """

    def __init__(
        self,
        num_processes: int | None = None,
        *,
        local_mode: bool = True,
        use_gpu: bool = False,  # noqa: ARG002 - API parity
        platform: str | None = None,
        env: dict[str, str] | None = None,
        timeout: float = 600.0,
        max_restarts: int = 0,
    ) -> None:
        self.num_processes = num_processes or 1
        self.local_mode = local_mode
        self.platform = platform
        self.extra_env = env or {}
        self.timeout = timeout
        # Spark-barrier recovery semantics (SURVEY.md §5 failure detection):
        # a failed stage is retried whole — all-or-nothing gang restarts.
        self.max_restarts = max_restarts

    # -- multi-host control plane --------------------------------------------
    def commands_for_hosts(
        self, fn: Callable | str, hosts: Sequence[str], coordinator_port: int = 29500
    ) -> list[str]:
        """One launch command per host for an external scheduler (the analogue
        of spark-submit's role): host 0 is the coordinator."""
        ref = fn_reference(fn)
        coord = f"{hosts[0]}:{coordinator_port}"
        return [
            sys.executable
            + " -m machine_learning_apache_spark_tpu.launcher.runner"
            + f" --fn {ref} --coordinator {coord}"
            + f" --num-processes {len(hosts)} --process-id {rank}"
            for rank, _ in enumerate(hosts)
        ]

    # -- local gang spawn ----------------------------------------------------
    def run(self, fn: Callable | str, *args: Any, **kwargs: Any) -> Any:
        """Spawn the gang, wait, return rank 0's result
        (``distributor.run(train_func)`` contract, ``distributed_cnn.py:231``)."""
        if not self.local_mode:
            raise RuntimeError(
                "cluster mode is driven by an external scheduler: use "
                "commands_for_hosts() to obtain per-host launch commands"
            )
        n = self.num_processes
        if n == 1 and not (self.platform or self.extra_env):
            # Single process: run inline, as the reference's sequential
            # scripts do (no rendezvous needed). With platform/env overrides
            # we must still spawn (they only apply to a fresh interpreter —
            # this one's JAX backend may already be initialized).
            fn = self._resolve(fn)
            return fn(*args, **kwargs)

        ref = fn_reference(fn)
        coord = f"127.0.0.1:{_free_port()}"
        workdir = tempfile.mkdtemp(prefix="mlspark_gang_")
        args_path = os.path.join(workdir, "args.pkl")
        with open(args_path, "wb") as f:
            pickle.dump((args, kwargs), f)

        try:
            attempt = 0
            while True:
                # Clear any stale result files from a failed attempt so a
                # restart can't return a dead rank's leftovers.
                for rank in range(n):
                    stale = os.path.join(workdir, f"result_{rank}.pkl")
                    if os.path.exists(stale):
                        os.unlink(stale)
                try:
                    return self._run_gang(ref, coord, workdir, args_path, n)
                except (RuntimeError, TimeoutError):
                    attempt += 1
                    if attempt > self.max_restarts:
                        raise
                    log.warning(
                        "gang attempt %d/%d failed; restarting whole gang "
                        "(Spark-barrier all-or-nothing semantics)",
                        attempt, self.max_restarts,
                    )
                    coord = f"127.0.0.1:{_free_port()}"  # stale port may linger
        finally:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)

    def _run_gang(
        self, ref: str, coord: str, workdir: str, args_path: str, n: int
    ) -> Any:
        procs: list[subprocess.Popen] = []
        result_paths = []
        for rank in range(n):
            result_path = os.path.join(workdir, f"result_{rank}.pkl")
            result_paths.append(result_path)
            env = dict(os.environ)
            env.update(self.extra_env)
            env["MLSPARK_COORDINATOR"] = coord
            env["MLSPARK_NUM_PROCESSES"] = str(n)
            env["MLSPARK_PROCESS_ID"] = str(rank)
            host, _, port = coord.partition(":")
            env["MASTER_ADDR"], env["MASTER_PORT"] = host, port
            env["WORLD_SIZE"], env["RANK"] = str(n), str(rank)
            if self.platform:
                # Both forms: the env var for vanilla images, MLSPARK_PLATFORM
                # for the runner's config-API override (the axon sitecustomize
                # ignores JAX_PLATFORMS — see runner.main).
                env["JAX_PLATFORMS"] = self.platform
                env["MLSPARK_PLATFORM"] = self.platform
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in sys.path if p
            )
            cmd = [
                sys.executable,
                "-m",
                "machine_learning_apache_spark_tpu.launcher.runner",
                "--fn", ref,
                "--args-file", args_path,
                "--result-file", result_path,
            ]
            procs.append(subprocess.Popen(cmd, env=env))
        log.info("spawned %d-process gang (coordinator %s)", n, coord)

        deadline = time.monotonic() + self.timeout
        try:
            self._wait_gang(procs, deadline)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()

        results = [self._read_result(path, rank) for rank, path in enumerate(result_paths)]
        errors = [r for r in results if r.error]
        if errors:
            # Ranks killed by the gang teardown leave placeholder errors;
            # surface the rank that actually crashed (its real traceback).
            primary = next(
                (r for r in errors if "produced no result" not in r.error), errors[0]
            )
            raise RuntimeError(
                "gang failed on rank(s) "
                + ", ".join(str(r.rank) for r in errors)
                + f":\n[rank {primary.rank}] {primary.error}"
            )
        return results[0].value

    def _wait_gang(self, procs: list[subprocess.Popen], deadline: float) -> None:
        """All-or-nothing barrier semantics: first nonzero exit kills the gang."""
        pending = set(range(len(procs)))
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"gang did not finish within {self.timeout}s; killing"
                )
            for rank in list(pending):
                code = procs[rank].poll()
                if code is None:
                    continue
                pending.discard(rank)
                if code != 0:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    # fall through: result files carry the traceback
            time.sleep(0.05)

    @staticmethod
    def _resolve(fn: Callable | str) -> Callable:
        return fn if callable(fn) else resolve_fn(fn)

    @staticmethod
    def _read_result(path: str, rank: int) -> WorkerResult:
        if not os.path.exists(path):
            return WorkerResult(rank=rank, error=f"rank {rank} produced no result (crashed?)")
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except Exception as e:
            # Truncated/corrupt file (e.g. the worker died mid-dump, or its
            # return value wasn't picklable): treat as a worker failure so the
            # gang error carries the rank, not a bare unpickling traceback.
            return WorkerResult(
                rank=rank, error=f"rank {rank} produced no result (unreadable result file: {e!r})"
            )


# API-parity alias: reference user code says TorchDistributor
# (distributed_cnn.py:227).
TorchDistributor = Distributor
