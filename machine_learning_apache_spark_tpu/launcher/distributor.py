"""Distributor — the TorchDistributor equivalent (reference C12).

The reference launches distributed training with
``TorchDistributor(num_processes=executors_n, local_mode=..., use_gpu=False)
.run(train_func)`` (``distributed_cnn.py:227-231``): Spark gang-schedules one
barrier task per process, sets the torch rendezvous env vars, pickles
``train_func`` with its module globals, and returns rank 0's result.

Design deltas (SURVEY.md §7 design stance):

- **Function by reference, not pickle-by-value**: the train function must be
  importable (``module:qualname`` or a module-level callable). This kills the
  reference's accidental re-execution of module-level downloads on every
  executor (quirk Q13) — each worker imports the module once, deliberately.
- **Rendezvous**: the launcher picks a free coordinator port and writes the
  ``{MLSPARK_COORDINATOR, NUM_PROCESSES, PROCESS_ID}`` env contract (plus the
  torch-style aliases) that ``launcher.coordinator`` maps onto
  ``jax.distributed.initialize`` (SURVEY.md §2.4).
- **Result**: rank 0's return value is actually returned (the reference's
  ``train_func``s return None yet assign the result — quirk Q7).
- **Gang failure semantics**: any worker dying kills the gang and raises —
  the Spark-barrier all-or-nothing behavior (SURVEY.md §5 failure detection).

``local_mode=True`` (the reference's bring-up path,
``distributed_multilayer_perceptron.py:179``) spawns all ranks on this host.
Multi-host mode emits the per-host command lines instead (control-plane
integration with an external scheduler; see ``commands_for_hosts``).
"""

from __future__ import annotations

import atexit
import os
import pickle
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Sequence

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.launcher.monitor import (
    GangFailure,
    GangMonitor,
    terminate_gang,
)
from machine_learning_apache_spark_tpu.utils import env as envcfg
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Process groups of gangs this interpreter spawned and has not yet reaped.
# Safety net against orphaned workers: the normal path unregisters after
# reaping, and the atexit sweep (plus tests/conftest.py's session-finish
# sweep) SIGKILLs whatever a crashed/interrupted driver left behind —
# otherwise a timed-out pytest run leaves rogue ranks burning CPU past the
# CI timeout.
_LIVE_PGIDS: set[int] = set()
_PGIDS_LOCK = threading.Lock()


def _register_gang(procs: Sequence[subprocess.Popen]) -> None:
    with _PGIDS_LOCK:
        _LIVE_PGIDS.update(p.pid for p in procs)


def _unregister_gang(procs: Sequence[subprocess.Popen]) -> None:
    with _PGIDS_LOCK:
        _LIVE_PGIDS.difference_update(p.pid for p in procs)


def kill_stray_gangs() -> int:
    """SIGKILL every registered-but-unreaped gang process group. Returns
    the number of groups signalled (0 in any healthy run)."""
    with _PGIDS_LOCK:
        pgids, stray = list(_LIVE_PGIDS), len(_LIVE_PGIDS)
        _LIVE_PGIDS.clear()
    for pgid in pgids:
        try:
            os.killpg(pgid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            stray -= 1
    if stray:
        log.warning("killed %d stray gang process group(s)", stray)
    return stray


atexit.register(kill_stray_gangs)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def fn_reference(fn: Callable | str) -> str:
    """``module:qualname`` reference for an importable function."""
    if isinstance(fn, str):
        if ":" not in fn:
            raise ValueError(f"function reference must be 'module:qualname', got {fn!r}")
        return fn
    module = getattr(fn, "__module__", None)
    qualname = getattr(fn, "__qualname__", None)
    if not module or not qualname or "<" in qualname:
        raise ValueError(
            f"{fn!r} is not an importable module-level function; the launcher "
            "runs functions by reference (no closure pickling — SURVEY.md Q13)"
        )
    return f"{module}:{qualname}"


def resolve_fn(ref: str) -> Callable:
    """Import a ``module:qualname`` reference (shared by Distributor and the
    per-worker runner)."""
    import importlib

    module, _, qual = fn_reference(ref).partition(":")
    obj: Any = importlib.import_module(module)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


@dataclass
class WorkerResult:
    rank: int
    value: Any = None
    error: str | None = None


class Distributor:
    """``Distributor(num_processes=N, local_mode=True).run(train_fn, *args)``.

    ``use_gpu`` is accepted for API parity with TorchDistributor and ignored
    (the accelerator is whatever the JAX platform provides; the reference
    always passed ``use_gpu=False`` anyway, ``distributed_cnn.py:230``).
    """

    def __init__(
        self,
        num_processes: int | None = None,
        *,
        local_mode: bool = True,
        use_gpu: bool = False,  # noqa: ARG002 - API parity
        platform: str | None = None,
        env: dict[str, str] | None = None,
        dp_mode: str | None = None,
        dp_overlap: bool | None = None,
        serve_kv_mode: str | None = None,
        serve_kv_dtype: str | None = None,
        telemetry_http: int | None = None,
        ingest: dict | None = None,
        timeout: float = 600.0,
        max_restarts: int = 0,
        heartbeat_interval: float = 1.0,
        heartbeat_timeout: float | None = 300.0,
        term_grace: float = 5.0,
        backoff_base: float = 0.5,
        backoff_max: float = 30.0,
        elastic: bool = False,
        elastic_min_world: int = 1,
        rank_restart_budget: int | None = None,
    ) -> None:
        self.num_processes = num_processes or 1
        self.local_mode = local_mode
        self.platform = platform
        self.extra_env = env or {}
        # Data-parallel update mode for the workers' fit() (parallel.zero
        # env contract): "zero1" opts the whole gang into the fused
        # sharded-update step via MLSPARK_DP_MODE. Kept as a first-class
        # knob (not just env=) so driver scripts read as intent, and
        # validated here — a typo must fail at Distributor construction,
        # not inside every worker after rendezvous.
        if dp_mode is not None and dp_mode not in ("replicated", "zero1"):
            raise ValueError(
                f"unknown dp_mode {dp_mode!r} (expected 'replicated' or "
                "'zero1')"
            )
        self.dp_mode = dp_mode
        # The zero1 overlap schedule rides the same contract: the boolean
        # knob becomes MLSPARK_ZERO1_OVERLAP in every worker
        # (Zero1Config.from_env resolves it; workers default to overlap
        # on when neither knob nor env is set).
        if dp_overlap is not None and not isinstance(dp_overlap, bool):
            raise ValueError(
                f"dp_overlap must be a bool or None, got {dp_overlap!r}"
            )
        self.dp_overlap = dp_overlap
        # Serving KV-cache mode, same env contract shape: the knob becomes
        # MLSPARK_SERVE_KV_MODE in every worker, which ServingEngine
        # resolves when kv_mode isn't passed explicitly ("paged" is the
        # engine default; "padded" selects the legacy rectangle path as
        # an equivalence oracle). Validated here so a typo fails in the
        # driver, not inside every rank after rendezvous.
        if serve_kv_mode is not None and serve_kv_mode not in (
            "padded", "paged"
        ):
            raise ValueError(
                f"unknown serve_kv_mode {serve_kv_mode!r} (expected "
                "'padded' or 'paged')"
            )
        self.serve_kv_mode = serve_kv_mode
        # Serving KV-store dtype, same contract: the knob becomes
        # MLSPARK_SERVE_KV_DTYPE in every worker ("float32" is the engine
        # default; "int8" quantizes paged KV pages with per-page scales).
        # ServingEngine revalidates against the resolved kv_mode — int8
        # with a padded/beam engine fails there with the full context.
        if serve_kv_dtype is not None and serve_kv_dtype not in (
            "float32", "int8"
        ):
            raise ValueError(
                f"unknown serve_kv_dtype {serve_kv_dtype!r} (expected "
                "'float32' or 'int8')"
            )
        self.serve_kv_dtype = serve_kv_dtype
        # Live observability plane, same env-contract shape: the knob
        # becomes MLSPARK_TELEMETRY_HTTP in every worker, which runner.main
        # resolves into a per-rank HTTP server. 0 means "ephemeral port per
        # rank" (the only sane choice for a local gang — fixed ports would
        # collide); each rank publishes its bound port in an
        # http_rank<k>.json sidecar for tools/gang_status.py to find.
        if telemetry_http is not None and not (
            0 <= int(telemetry_http) <= 65535
        ):
            raise ValueError(
                f"telemetry_http must be a port in [0, 65535] or None, "
                f"got {telemetry_http!r}"
            )
        self.telemetry_http = telemetry_http
        # Input-pipeline plumbing, same shape as dp_mode: the
        # Distributor(ingest={"buffer": 4, "tail": "pad", ...}) knob
        # becomes MLSPARK_INGEST_* in every worker's environment (the
        # contract ingest.IngestConfig.from_env resolves), validated at
        # construction so a typo'd knob fails in the driver, not inside
        # every rank after rendezvous.
        if ingest:
            from machine_learning_apache_spark_tpu.ingest.config import (
                validate_ingest_knobs,
            )

            self.ingest_env = validate_ingest_knobs(ingest)
        else:
            self.ingest_env = {}
        self.timeout = timeout
        # Spark-barrier recovery semantics (SURVEY.md §5 failure detection):
        # a failed stage is retried whole — all-or-nothing gang restarts.
        self.max_restarts = max_restarts
        # Liveness detection (docs/FAULT_TOLERANCE.md): each worker touches
        # a per-rank heartbeat file every `heartbeat_interval`; a rank silent
        # past `heartbeat_timeout` is declared stalled and the gang torn
        # down (None disables — exit codes and the deadline still apply).
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        # Teardown escalation: SIGTERM, wait `term_grace`, then SIGKILL.
        self.term_grace = term_grace
        # Restart pacing: exponential backoff with jitter, so co-failing
        # gangs on one host don't re-stampede the same resource in lockstep.
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        # Elastic shrink policy (docs/FAULT_TOLERANCE.md "Elastic
        # resume"): when one rank keeps failing past its per-rank restart
        # budget (`rank_restart_budget`, defaulting to `max_restarts`),
        # it is judged PERMANENTLY LOST — a preempted chip, a bad host.
        # With elastic=True the gang retries at world-1 (never below
        # `elastic_min_world`) instead of raising, and the workers see
        # MLSPARK_ELASTIC=1 so fit(resume=True) reshards the old world's
        # checkpoints onto the shrunken mesh (train/reshard.py). With
        # elastic=False (but a budget set) the exhaustion raises a
        # GangFailure with permanent=True naming the rank, cause, and
        # attempt count. Deadline expiries never count against a rank —
        # they blame the whole gang, not a member.
        self.elastic = bool(elastic)
        if int(elastic_min_world) < 1:
            raise ValueError(
                f"elastic_min_world must be >= 1, got {elastic_min_world}"
            )
        if int(elastic_min_world) > self.num_processes:
            raise ValueError(
                f"elastic_min_world={elastic_min_world} exceeds "
                f"num_processes={self.num_processes}"
            )
        self.elastic_min_world = int(elastic_min_world)
        if rank_restart_budget is not None and int(rank_restart_budget) < 0:
            raise ValueError(
                f"rank_restart_budget must be >= 0 or None, got "
                f"{rank_restart_budget}"
            )
        self.rank_restart_budget = (
            None if rank_restart_budget is None else int(rank_restart_budget)
        )

    # -- multi-host control plane --------------------------------------------
    def commands_for_hosts(
        self, fn: Callable | str, hosts: Sequence[str], coordinator_port: int = 29500
    ) -> list[str]:
        """One launch command per host for an external scheduler (the analogue
        of spark-submit's role): host 0 is the coordinator."""
        ref = fn_reference(fn)
        coord = f"{hosts[0]}:{coordinator_port}"
        return [
            sys.executable
            + " -m machine_learning_apache_spark_tpu.launcher.runner"
            + f" --fn {ref} --coordinator {coord}"
            + f" --num-processes {len(hosts)} --process-id {rank}"
            for rank, _ in enumerate(hosts)
        ]

    # -- local gang spawn ----------------------------------------------------
    def run(self, fn: Callable | str, *args: Any, **kwargs: Any) -> Any:
        """Spawn the gang, wait, return rank 0's result
        (``distributor.run(train_func)`` contract, ``distributed_cnn.py:231``)."""
        if not self.local_mode:
            raise RuntimeError(
                "cluster mode is driven by an external scheduler: use "
                "commands_for_hosts() to obtain per-host launch commands"
            )
        n = self.num_processes
        if n == 1 and not (self.platform or self.extra_env):
            # Single process: run inline, as the reference's sequential
            # scripts do (no rendezvous needed). With platform/env overrides
            # we must still spawn (they only apply to a fresh interpreter —
            # this one's JAX backend may already be initialized).
            fn = self._resolve(fn)
            return fn(*args, **kwargs)

        ref = fn_reference(fn)
        coord = f"127.0.0.1:{_free_port()}"
        workdir = tempfile.mkdtemp(prefix="mlspark_gang_")
        args_path = os.path.join(workdir, "args.pkl")
        with open(args_path, "wb") as f:
            pickle.dump((args, kwargs), f)

        try:
            attempt = 0
            # Per-rank failure counts since the last shrink — the elastic
            # policy's permanent-loss ledger (deadline expiries excluded:
            # they blame the gang, not a member).
            rank_failures: dict[int, int] = {}
            while True:
                # Clear any stale result/heartbeat files from a failed
                # attempt so a restart can't return a dead rank's leftovers
                # (or judge liveness off a corpse's last beat). Sweep the
                # ORIGINAL world's files — after a shrink, a departed
                # rank's leftovers must not linger either.
                for rank in range(self.num_processes):
                    for name in (f"result_{rank}.pkl", f"heartbeat_{rank}"):
                        stale = os.path.join(workdir, name)
                        if os.path.exists(stale):
                            os.unlink(stale)
                try:
                    with telemetry.span(
                        "launcher.gang_attempt",
                        attempt=attempt, num_processes=n,
                    ):
                        value = self._run_gang(
                            ref, coord, workdir, args_path, n, attempt
                        )
                    self._write_telemetry_report(workdir)
                    return value
                except GangFailure as failure:
                    attempt += 1
                    budget = (
                        self.max_restarts
                        if self.rank_restart_budget is None
                        else self.rank_restart_budget
                    )
                    lost: int | None = None
                    if failure.rank is not None and failure.cause != "deadline":
                        rank_failures[failure.rank] = (
                            rank_failures.get(failure.rank, 0) + 1
                        )
                        if rank_failures[failure.rank] > budget:
                            lost = failure.rank
                    if lost is not None and (
                        self.elastic or self.rank_restart_budget is not None
                    ):
                        fails = rank_failures[lost]
                        if not self.elastic:
                            telemetry.annotate(
                                "launcher.gang_exhausted",
                                attempt=attempt, rank=lost,
                                cause=failure.cause,
                            )
                            raise GangFailure(
                                f"rank {lost} permanently lost "
                                f"(cause={failure.cause}) after {fails} "
                                f"failed attempt(s) — per-rank restart "
                                f"budget {budget} exhausted and elastic "
                                "resume is disabled",
                                rank=lost, cause=failure.cause,
                                attempt=attempt,
                                exit_code=failure.exit_code,
                                permanent=True,
                            ) from failure
                        if n - 1 < self.elastic_min_world:
                            telemetry.annotate(
                                "launcher.gang_exhausted",
                                attempt=attempt, rank=lost,
                                cause=failure.cause,
                            )
                            raise GangFailure(
                                f"rank {lost} permanently lost "
                                f"(cause={failure.cause}) after {fails} "
                                f"failed attempt(s) and the gang cannot "
                                f"shrink below elastic_min_world="
                                f"{self.elastic_min_world} (world is {n})",
                                rank=lost, cause=failure.cause,
                                attempt=attempt,
                                exit_code=failure.exit_code,
                                permanent=True,
                            ) from failure
                        telemetry.annotate(
                            "launcher.gang_shrink",
                            old_world=n, new_world=n - 1, rank=lost,
                            cause=failure.cause, failures=fails,
                        )
                        log.warning(
                            "rank %d permanently lost (cause=%s, %d "
                            "failure(s) > budget %d); shrinking gang "
                            "%d -> %d and resuming elastically from the "
                            "group checkpoints",
                            lost, failure.cause, fails, budget, n, n - 1,
                        )
                        n -= 1
                        attempt = 0
                        rank_failures.clear()
                        time.sleep(min(self.backoff_max, self.backoff_base))
                        coord = f"127.0.0.1:{_free_port()}"
                        continue
                    telemetry.annotate(
                        "launcher.gang_retry" if attempt <= self.max_restarts
                        else "launcher.gang_exhausted",
                        attempt=attempt, rank=failure.rank,
                        cause=failure.cause,
                    )
                    if attempt > self.max_restarts:
                        raise
                    delay = min(
                        self.backoff_max,
                        self.backoff_base * (2 ** (attempt - 1)),
                    ) * (0.5 + random.random() / 2)  # full-jitter-lite
                    log.warning(
                        "gang attempt %d/%d failed (rank=%s cause=%s); "
                        "restarting whole gang in %.2fs (Spark-barrier "
                        "all-or-nothing semantics)",
                        attempt, self.max_restarts, failure.rank,
                        failure.cause, delay,
                    )
                    time.sleep(delay)
                    coord = f"127.0.0.1:{_free_port()}"  # stale port may linger
        finally:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)

    def _telemetry_out_dir(self, workdir: str) -> str:
        """Where this gang's telemetry files land — the same precedence the
        worker env gets in ``_run_gang`` (explicit env= > inherited env >
        the ephemeral workdir)."""
        return (
            self.extra_env.get("MLSPARK_TELEMETRY_DIR")
            or envcfg.get_str("MLSPARK_TELEMETRY_DIR")
            or workdir
        )

    def _write_telemetry_report(self, workdir: str) -> None:
        """Rank-0-side gang merge: after a successful run, fold the per-rank
        ``telemetry_rank<k>.jsonl`` exports into ``telemetry_report.json``
        (+ ``.md``) in the telemetry dir. Best-effort — reporting must never
        fail a run that trained fine."""
        if not telemetry.enabled():
            return
        try:
            tdir = self._telemetry_out_dir(workdir)
            from machine_learning_apache_spark_tpu.telemetry import aggregate

            if not aggregate.find_rank_files(tdir):
                return
            report = aggregate.merge_gang_dir(tdir)
            import json

            with open(os.path.join(tdir, "telemetry_report.json"), "w") as f:
                json.dump(report, f, indent=2)
                f.write("\n")
            with open(os.path.join(tdir, "telemetry_report.md"), "w") as f:
                f.write(aggregate.render_markdown(report))
            log.info(
                "telemetry report merged from %d rank(s) into %s",
                len(report["ranks"]), tdir,
            )
        except Exception:
            log.exception("telemetry report generation failed (ignored)")

    def _run_gang(
        self,
        ref: str,
        coord: str,
        workdir: str,
        args_path: str,
        n: int,
        attempt: int = 0,
    ) -> Any:
        procs: list[subprocess.Popen] = []
        result_paths, heartbeat_paths = [], []
        for rank in range(n):
            result_path = os.path.join(workdir, f"result_{rank}.pkl")
            heartbeat_path = os.path.join(workdir, f"heartbeat_{rank}")
            result_paths.append(result_path)
            heartbeat_paths.append(heartbeat_path)
            env = dict(os.environ)
            # A driver running under the test harness carries
            # --xla_force_host_platform_device_count in XLA_FLAGS (virtual
            # multi-device CPU). Workers must NOT inherit it: the gang
            # contract is one device per rank (world == num_processes), and
            # an inherited 8x multiplier breaks every worker-side mesh.
            # Explicit Distributor(env=...) still wins (applied below).
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" in flags:
                kept = " ".join(
                    f for f in flags.split()
                    if "xla_force_host_platform_device_count" not in f
                )
                if kept:
                    env["XLA_FLAGS"] = kept
                else:
                    env.pop("XLA_FLAGS", None)
            # DP-mode plumbing: the constructor knob becomes the workers'
            # MLSPARK_DP_MODE (fit() resolves it when dp_mode isn't passed
            # explicitly); an inherited MLSPARK_DP_MODE flows through
            # dict(os.environ) above, and explicit env= still wins below.
            # Writes go through the registry (envcfg.put_into): a typo'd
            # contract name fails here at the driver, not as a silently
            # ignored variable in every rank.
            if self.dp_mode is not None:
                envcfg.put_into(env, "MLSPARK_DP_MODE", self.dp_mode)
            if self.dp_overlap is not None:
                envcfg.put_into(
                    env, "MLSPARK_ZERO1_OVERLAP",
                    "1" if self.dp_overlap else "0",
                )
            # Serving KV mode rides the same contract (constructor >
            # inherited env; explicit env= still wins below).
            if self.serve_kv_mode is not None:
                envcfg.put_into(env, "MLSPARK_SERVE_KV_MODE", self.serve_kv_mode)
            if self.serve_kv_dtype is not None:
                envcfg.put_into(env, "MLSPARK_SERVE_KV_DTYPE", self.serve_kv_dtype)
            # Observability-plane port knob, same contract shape.
            if self.telemetry_http is not None:
                envcfg.put_into(env, "MLSPARK_TELEMETRY_HTTP", self.telemetry_http)
            # Elastic opt-in rides the same contract: the workers' fit()
            # resolves MLSPARK_ELASTIC when elastic= isn't passed, so a
            # shrunken gang reshards old-topology checkpoints instead of
            # refusing them (train/reshard.py).
            if self.elastic:
                envcfg.put_into(env, "MLSPARK_ELASTIC", "1")
            # Ingest knobs ride the same contract: constructor > inherited
            # env (explicit env= still wins below).
            env.update(self.ingest_env)
            env.update(self.extra_env)
            # Workers default their telemetry output (rank JSONLs, flight
            # dumps) next to the heartbeat files; an inherited or explicit
            # MLSPARK_TELEMETRY_DIR (e.g. a persistent dir from the fault
            # drill) wins — the workdir is ephemeral (rmtree'd below).
            env.setdefault("MLSPARK_TELEMETRY_DIR", workdir)
            envcfg.put_into(env, "MLSPARK_COORDINATOR", coord)
            envcfg.put_into(env, "MLSPARK_NUM_PROCESSES", n)
            envcfg.put_into(env, "MLSPARK_PROCESS_ID", rank)
            envcfg.put_into(env, "MLSPARK_GANG_ATTEMPT", attempt)
            envcfg.put_into(env, "MLSPARK_HEARTBEAT_FILE", heartbeat_path)
            envcfg.put_into(
                env, "MLSPARK_HEARTBEAT_INTERVAL", self.heartbeat_interval
            )
            host, _, port = coord.partition(":")
            env["MASTER_ADDR"], env["MASTER_PORT"] = host, port
            env["WORLD_SIZE"], env["RANK"] = str(n), str(rank)
            if self.platform:
                # Both forms: the env var for vanilla images, MLSPARK_PLATFORM
                # for the runner's config-API override (the axon sitecustomize
                # ignores JAX_PLATFORMS — see runner.main).
                env["JAX_PLATFORMS"] = self.platform
                envcfg.put_into(env, "MLSPARK_PLATFORM", self.platform)
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in sys.path if p
            )
            cmd = [
                sys.executable,
                "-m",
                "machine_learning_apache_spark_tpu.launcher.runner",
                "--fn", ref,
                "--args-file", args_path,
                "--result-file", result_path,
            ]
            # start_new_session: each worker leads its own process group, so
            # teardown signals reach the worker AND anything it spawned.
            procs.append(
                subprocess.Popen(cmd, env=env, start_new_session=True)
            )
        _register_gang(procs)
        log.info(
            "spawned %d-process gang (coordinator %s, attempt %d)",
            n, coord, attempt,
        )

        try:
            failure = self._wait_gang(procs, heartbeat_paths)
        finally:
            # Belt and suspenders for non-GangFailure exits (KeyboardInterrupt
            # etc.): nothing outlives the attempt.
            terminate_gang(procs, grace=0.0)
            _unregister_gang(procs)

        results = [self._read_result(path, rank) for rank, path in enumerate(result_paths)]
        errors = [r for r in results if r.error]
        if failure is None and not errors:
            return results[0].value

        # Ranks killed by the gang teardown leave placeholder errors;
        # surface the rank that actually crashed (its real traceback). A
        # rank with only a placeholder is an EFFECT of teardown, never the
        # blamed cause — a deadline expiry, where every rank is healthy but
        # slow, must keep rank=None.
        real = next(
            (r for r in errors if "produced no result" not in r.error), None
        )
        primary = real or (errors[0] if errors else None)
        detail = (
            f"\n[rank {primary.rank}] {primary.error}" if primary else ""
        )
        cause = failure.cause if failure is not None else "exit"
        raise GangFailure(
            "gang failed on rank(s) "
            + (", ".join(str(r.rank) for r in errors) or "?")
            + f" (cause={cause}, attempt={attempt})"
            + (f": {failure}" if failure is not None else "")
            + detail,
            rank=(
                failure.rank if failure is not None and failure.rank is not None
                else (real.rank if real else None)
            ),
            cause=cause,
            attempt=attempt,
            exit_code=failure.exit_code if failure is not None else None,
        )

    def _wait_gang(
        self,
        procs: list[subprocess.Popen],
        heartbeat_paths: list[str] | None = None,
    ) -> GangFailure | None:
        """All-or-nothing barrier semantics, delegated to a ``GangMonitor``
        thread: the first nonzero exit, stalled heartbeat, or deadline
        expiry tears the gang down (SIGTERM -> SIGKILL). Returns the
        detected failure, or None if every rank exited 0."""
        watcher = GangMonitor(
            procs,
            heartbeat_paths,
            timeout=self.timeout,
            heartbeat_timeout=self.heartbeat_timeout,
            grace=self.term_grace,
        )
        watcher.start()
        while watcher.is_alive():
            # join with a timeout so the driver stays interruptible
            # (Ctrl-C in a notebook must not wedge behind a daemon join).
            watcher.join(timeout=1.0)
        return watcher.failure

    @staticmethod
    def _resolve(fn: Callable | str) -> Callable:
        return fn if callable(fn) else resolve_fn(fn)

    @staticmethod
    def _read_result(path: str, rank: int) -> WorkerResult:
        if not os.path.exists(path):
            return WorkerResult(rank=rank, error=f"rank {rank} produced no result (crashed?)")
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except Exception as e:
            # Truncated/corrupt file (e.g. the worker died mid-dump, or its
            # return value wasn't picklable): treat as a worker failure so the
            # gang error carries the rank, not a bare unpickling traceback.
            return WorkerResult(
                rank=rank, error=f"rank {rank} produced no result (unreadable result file: {e!r})"
            )


# API-parity alias: reference user code says TorchDistributor
# (distributed_cnn.py:227).
TorchDistributor = Distributor
