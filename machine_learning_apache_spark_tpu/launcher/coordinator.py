"""Rendezvous coordination — env vars → ``jax.distributed.initialize``.

The reference bootstraps its process group from
``{MASTER_ADDR, MASTER_PORT, WORLD_SIZE, RANK, LOCAL_RANK}`` env vars set
either manually (``pytorch_multilayer_perceptron.py:15-21``) or by
TorchDistributor under spark-submit (commented fallback block,
``distributed_cnn.py:22-27``). The TPU mapping (SURVEY.md §2.4):

    MASTER_ADDR:MASTER_PORT → coordinator_address
    WORLD_SIZE              → num_processes
    RANK                    → process_id

Single-process runs (no env vars, world size 1) skip initialization entirely —
the single-controller JAX runtime needs no rendezvous, just like the
reference's sequential scripts never call ``init_process_group``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax

from machine_learning_apache_spark_tpu.config import SessionConfig
from machine_learning_apache_spark_tpu.utils import env as envcfg

# Framework-native env names, with the reference's torch names as fallbacks.
ENV_COORDINATOR = "MLSPARK_COORDINATOR"
ENV_NUM_PROCESSES = "MLSPARK_NUM_PROCESSES"
ENV_PROCESS_ID = "MLSPARK_PROCESS_ID"

_initialized = False


@dataclass
class RendezvousSpec:
    coordinator_address: str  # "host:port"
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls, conf: SessionConfig | None = None) -> "RendezvousSpec | None":
        """Resolve the rendezvous from (in priority order) explicit session
        conf, framework env vars, then the reference's torch-style env vars.
        Returns None when this is a single-process run."""
        conf = conf or SessionConfig()
        if conf.coordinator_address and conf.num_processes > 1:
            return cls(conf.coordinator_address, conf.num_processes, max(conf.process_id, 0))

        addr = envcfg.get_str(ENV_COORDINATOR)
        if addr is None and "MASTER_ADDR" in os.environ:
            addr = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', '29500')}"
        world = envcfg.get_int(ENV_NUM_PROCESSES, default=None)
        if world is None:
            world = int(os.environ.get("WORLD_SIZE", "1"))
        rank = envcfg.get_int(ENV_PROCESS_ID, default=None)
        if rank is None:
            rank = int(os.environ.get("RANK", "0"))
        if addr is None or world <= 1:
            return None
        return cls(addr, world, rank)

    def apply_env(self, env: dict[str, str]) -> dict[str, str]:
        """Write this spec into an env mapping (what the launcher sets on each
        spawned worker — TorchDistributor's env distribution step)."""
        env[ENV_COORDINATOR] = self.coordinator_address
        env[ENV_NUM_PROCESSES] = str(self.num_processes)
        env[ENV_PROCESS_ID] = str(self.process_id)
        # Torch-style aliases so reference-shaped user code keeps working.
        host, _, port = self.coordinator_address.partition(":")
        env["MASTER_ADDR"] = host
        env["MASTER_PORT"] = port or "29500"
        env["WORLD_SIZE"] = str(self.num_processes)
        env["RANK"] = str(self.process_id)
        return env


def initialize_from_env(conf: SessionConfig | None = None) -> RendezvousSpec | None:
    """The ``dist.init_process_group('gloo')`` analogue
    (``distributed_cnn.py:152``): idempotent multi-host bootstrap."""
    global _initialized
    spec = RendezvousSpec.from_env(conf)
    if spec is None or _initialized:
        return spec
    # The CPU backend has no native cross-process collectives ("Multiprocess
    # computations aren't implemented on the CPU backend") — gloo is its
    # gloo. Opt in before the backend initializes so CPU gangs (the
    # reference's local_mode bring-up path AND the fault-drill test gangs)
    # can run real psums/allgathers; TPU backends ignore the setting.
    platforms = os.environ.get("JAX_PLATFORMS", jax.config.jax_platforms or "")
    if "cpu" in str(platforms).split(","):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - older/newer jax: name moved/absent
            pass
    jax.distributed.initialize(
        coordinator_address=spec.coordinator_address,
        num_processes=spec.num_processes,
        process_id=spec.process_id,
    )
    _initialized = True
    return spec


def shutdown() -> None:
    """``destroy_process_group()`` analogue (``distributed_cnn.py:193``)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
