"""TinyVGG-style CNN for FashionMNIST-class workloads.

Reference: ``FashionMNISTModel`` (``pytorch_cnn.py:12-49``, duplicated
``distributed_cnn.py:47-86``): two conv blocks of
[Conv3x3 s1 p1 → ReLU → Conv3x3 → ReLU → MaxPool2] then Flatten →
Linear(hidden·7·7 → classes), with ``input_shape=1, hidden_units=10``
(``pytorch_cnn.py:94-96``).

TPU-first deltas: NHWC layout (XLA:TPU's native conv layout — NCHW would
insert transposes around every conv), and the classifier head infers its
input width from the actual spatial shape instead of hardcoding 7·7.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TinyVGG(nn.Module):
    """Two-block VGG mini. Input ``[B, H, W, C]`` (NHWC), e.g. 28×28×1.

    ``dtype`` is the compute dtype (bfloat16 feeds the MXU at full rate on
    TPU); params stay float32 (flax's param_dtype default) and logits are
    returned float32 so the loss/softmax never run in half precision.
    """

    hidden_units: int = 10
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        # Accepted for zoo-wide signature uniformity; TinyVGG has no dropout.
        del deterministic
        x = x.astype(self.dtype)
        for block in range(2):
            for conv in range(2):
                x = nn.Conv(
                    self.hidden_units,
                    kernel_size=(3, 3),
                    strides=1,
                    padding=1,
                    dtype=self.dtype,
                    name=f"block{block}_conv{conv}",
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return logits.astype(jnp.float32)


# The reference's class name, for API-parity imports.
FashionMNISTModel = TinyVGG
