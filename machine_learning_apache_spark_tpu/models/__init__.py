"""Model zoo — MLP, CNN, LSTM, encoder-decoder Transformer.

One library replacing the reference's copy-pasted per-script model classes
(C2/C5/C8 duplicated across sequential/distributed scripts, SURVEY.md §2.1)
and its ``transformer.py`` module library (C14-C23).
"""

from machine_learning_apache_spark_tpu.models.mlp import MLP
from machine_learning_apache_spark_tpu.models.cnn import TinyVGG, FashionMNISTModel
from machine_learning_apache_spark_tpu.models.lstm import LSTMClassifier
from machine_learning_apache_spark_tpu.models.transformer import (
    Transformer,
    beam_translate,
    greedy_translate,
    greedy_translate_cached,
    sample_translate,
    Encoder,
    Decoder,
    TransformerConfig,
)

__all__ = [
    "MLP",
    "TinyVGG",
    "FashionMNISTModel",
    "LSTMClassifier",
    "Transformer",
    "beam_translate",
    "greedy_translate",
    "greedy_translate_cached",
    "sample_translate",
    "Encoder",
    "Decoder",
    "TransformerConfig",
]
