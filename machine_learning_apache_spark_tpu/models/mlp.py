"""Multilayer perceptron.

The reference defines this twice (``Multilayer_perceptor``,
``pytorch_multilayer_perceptron.py:33-42`` and
``distributed_multilayer_perceptron.py:44-53``): Linear stack with Sigmoid
between layers and no final activation. Layer spec follows MLlib's
full-topology convention ``layers=[in, hidden..., out]``
(``mllib_multilayer_perceptron_classifier.py:32`` uses ``[4, 5, 4, 3]``).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """``MLP(layers=(4, 5, 4, 3))`` — the reference MLP family (C2).

    ``layers[0]`` is the expected input width (validated), the rest are layer
    output widths. ``activation`` sits between layers only; logits come out
    raw for a downstream softmax cross-entropy.

    ``tp_rules=True`` annotates the Dense kernels with logical axis names
    (alternating ``("embed", "mlp")`` / ``("mlp", "embed")`` — the classic
    column-then-row parallel pairing) so ``parallel.tensor_parallel`` can
    place them over a mesh ``"model"`` axis. Off by default: the plain
    reference model carries no partitioning metadata, and annotated inits
    return boxed ``nn.Partitioned`` leaves that callers must unbox or
    place. Hidden widths must divide the model-axis size to actually
    shard (non-divisible dims fall back to replicated, loudly).
    """

    layers: Sequence[int] = (4, 5, 4, 3)
    activation: Callable[[jnp.ndarray], jnp.ndarray] = nn.sigmoid
    tp_rules: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True) -> jnp.ndarray:
        # `deterministic` is accepted (and ignored — no dropout here) so the
        # zoo shares one train/eval loss signature.
        del deterministic
        if x.shape[-1] != self.layers[0]:
            raise ValueError(
                f"MLP expects {self.layers[0]} input features, got {x.shape[-1]}"
            )
        for i, width in enumerate(self.layers[1:]):
            if self.tp_rules:
                names = ("embed", "mlp") if i % 2 == 0 else ("mlp", "embed")
                kernel_init = nn.with_partitioning(
                    nn.initializers.lecun_normal(), names
                )
                x = nn.Dense(width, name=f"dense_{i}", kernel_init=kernel_init)(x)
            else:
                x = nn.Dense(width, name=f"dense_{i}")(x)
            if i < len(self.layers) - 2:
                x = self.activation(x)
        return x
