"""LSTM text classifier.

Reference: ``LSTM`` (``pytorch_lstm.py:94-119``, drifted duplicate
``distributed_lstm.py:110-135``): Embedding → 2-layer ``nn.LSTM``
(batch_first, dropout=0.5 between layers) → Linear head, with explicit
``(hidden, mem)`` state threading through ``forward`` and zero-init state per
batch (``pytorch_lstm.py:153-154``). Quirk Q10 (head width hardcoded to 32,
``padding_idx`` passed the string ``'0'``) is fixed: the head uses
``hidden_size`` and padding embeds are simply trained.

TPU-first design (SURVEY.md §7 "hard parts"): torch's fused multi-layer LSTM
kernel becomes ``jax.lax.scan`` over time with the *input-side* gate
projection hoisted out of the scan — ``x @ W_x`` for all timesteps is one
large ``[B·S, E]×[E, 4H]`` matmul the MXU tiles efficiently, leaving only the
``[B, H]×[H, 4H]`` recurrent matmul inside the sequential loop. Inter-layer
dropout matches torch's ``dropout=0.5`` placement (not on the last layer's
output).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class LSTMLayer(nn.Module):
    """One recurrent layer: ``lax.scan`` of the LSTM cell over time.

    Gate order follows the standard (i, f, g, o) convention. Carries are
    ``(h, c)`` with shape ``[B, hidden]`` each.
    """

    hidden_size: int

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, state: tuple[jnp.ndarray, jnp.ndarray] | None = None
    ):
        batch, _seq, in_dim = x.shape
        h0, c0 = state if state is not None else (
            jnp.zeros((batch, self.hidden_size), x.dtype),
            jnp.zeros((batch, self.hidden_size), x.dtype),
        )
        w_x = self.param(
            "w_x", nn.initializers.lecun_normal(), (in_dim, 4 * self.hidden_size)
        )
        w_h = self.param(
            "w_h", nn.initializers.orthogonal(), (self.hidden_size, 4 * self.hidden_size)
        )
        bias = self.param("bias", nn.initializers.zeros_init(), (4 * self.hidden_size,))

        # Input projection for the whole sequence at once: one big MXU matmul
        # instead of S small ones inside the scan.
        gates_x = jnp.einsum("bse,eh->bsh", x, w_x) + bias

        def cell(carry, gx):
            h, c = carry
            gates = gx + h @ w_h
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = nn.sigmoid(f) * c + nn.sigmoid(i) * jnp.tanh(g)
            h = nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        (h_n, c_n), ys = jax.lax.scan(
            cell, (h0, c0), jnp.swapaxes(gates_x, 0, 1)
        )
        return jnp.swapaxes(ys, 0, 1), (h_n, c_n)


class LSTMClassifier(nn.Module):
    """Embedding → stacked LSTM → Dense head (reference C8).

    ``__call__`` accepts and returns the explicit per-layer ``(h, c)`` states
    the reference threads manually; passing ``None`` zero-initializes them
    (``pytorch_lstm.py:153-154``). Returns per-timestep logits ``[B, S, C]``;
    the classification recipe takes the last timestep
    (``pytorch_lstm.py:160`` uses ``pred[:, -1, :]``).
    """

    vocab_size: int
    embed_dim: int = 32
    hidden_size: int = 32
    num_classes: int = 4
    num_layers: int = 2
    dropout: float = 0.5

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        state: list[tuple[jnp.ndarray, jnp.ndarray]] | None = None,
        *,
        deterministic: bool = True,
        return_state: bool = False,
    ):
        x = nn.Embed(self.vocab_size, self.embed_dim, name="embedding")(tokens)
        new_state = []
        for layer in range(self.num_layers):
            layer_state = state[layer] if state is not None else None
            x, s = LSTMLayer(self.hidden_size, name=f"lstm_{layer}")(x, layer_state)
            new_state.append(s)
            if layer < self.num_layers - 1:
                x = nn.Dropout(self.dropout, deterministic=deterministic)(x)
        logits = nn.Dense(self.num_classes, name="head")(x)
        if return_state:
            return logits, new_state
        return logits
