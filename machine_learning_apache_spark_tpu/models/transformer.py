"""Encoder-decoder Transformer.

Reference: the 11-class ``transformer.py`` module library (C14-C23,
SURVEY.md §2.1) used by the en→de MT driver
(``pytorch_machine_translator.py:120``: d_model=512, ffn=1024, heads=8,
drop=0.1, layers=1, max_seq=200).

Correct-semantics deltas from the reference (SURVEY.md §2.5):
- Q9: masks are boolean (True = attendable) applied ``where(mask, s, -inf)``
  before softmax — never added.
- Q8: cross-attention reshapes Q with the *decoder's* length and K/V with the
  *encoder's*; src/trg sequence lengths are independent.
- C15: positional encodings are a trace-time constant, not recomputed and
  re-transferred per forward.
- C18's hand-rolled LayerNorm is ``nn.LayerNorm`` (same math, fused by XLA).

Structure is post-LN residual (``x = LN(x + drop(sublayer(x)))``) matching
``transformer.py:130-139``. Attention runs through the shared ops core, which
dispatches to the Pallas flash kernel when maskless/causal on TPU.

Tensor-parallel seam: every Dense hidden axis is annotated with the logical
axis names ``("embed", "mlp"/"heads")`` via ``nn.with_partitioning`` — the
``parallel`` package maps these onto the mesh's ``"model"`` axis for TP runs
and to unsharded for single-chip runs.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from machine_learning_apache_spark_tpu.ops.attention import (
    NEG_INF,
    dot_product_attention,
    ragged_paged_attention,
)
from machine_learning_apache_spark_tpu.ops.masks import (
    combine_masks,
    make_causal_mask,
    make_padding_mask,
)
from machine_learning_apache_spark_tpu.ops.positional import sinusoidal_encoding


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Hyperparameters — the reference ctor signature (``transformer.py:256-267``)
    plus compute dtype. Defaults are the MT driver's
    (``pytorch_machine_translator.py:108-117``)."""

    src_vocab_size: int
    trg_vocab_size: int
    d_model: int = 512
    ffn_hidden: int = 1024
    num_heads: int = 8
    num_layers: int = 1
    dropout: float = 0.1
    max_len: int = 200
    pad_id: int = 0
    dtype: jnp.dtype = jnp.float32  # bfloat16 for MXU-native training
    # Extra all-zero-target columns on the LM head so its vocab dim divides
    # a tensor-parallel "model" axis (Megatron-style vocab padding). Logits
    # are sliced back to trg_vocab_size before they leave the model, so
    # losses/decoding are exactly vocab-sized regardless of padding.
    logit_pad: int = 0
    # Rematerialize encoder/decoder layers under autodiff (jax.checkpoint):
    # activations inside each layer are recomputed in the backward instead
    # of saved — O(num_layers) → O(1) layer activations live at once, the
    # FLOPs-for-HBM trade that makes long-context training fit.
    remat: bool = False
    # Mixture-of-experts FFN (models.moe): 0 = dense FFN (the reference's
    # C19); N > 0 replaces every FFN with N switch-routed experts whose
    # weights shard over the mesh "expert" axis. The Switch load-balancing
    # aux losses are sown into the "losses" collection — training code adds
    # moe_aux_weight × their mean to the task loss.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2


def _dense(features: int, cfg: TransformerConfig, name: str, logical_out: str):
    """Dense with TP logical partitioning on (in, out) kernel axes."""
    return nn.Dense(
        features,
        dtype=cfg.dtype,
        name=name,
        kernel_init=nn.with_partitioning(
            nn.initializers.lecun_normal(), ("embed", logical_out)
        ),
    )


class SentenceEmbedding(nn.Module):
    """Token embedding + positional encoding + dropout (C16,
    ``transformer.py:44-62``), with the PE table cached (C15 fix)."""

    vocab_size: int
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        *,
        deterministic: bool = True,
        position_offset: jnp.ndarray | int = 0,
        positions: jnp.ndarray | None = None,
    ):
        x = nn.Embed(
            self.vocab_size,
            self.cfg.d_model,
            dtype=self.cfg.dtype,
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(stddev=0.02), (None, "embed")
            ),
            name="embed",
        )(tokens)
        # position_offset shifts the PE window for incremental decoding
        # (token t of the generation loop gets PE row t, not 0). The table
        # covers max(cfg.max_len, L) so static sequences longer than max_len
        # keep working; only dynamic offsets are bounded by max_len.
        table = sinusoidal_encoding(
            max(self.cfg.max_len, tokens.shape[-1]),
            self.cfg.d_model,
            self.cfg.dtype,
        )
        if positions is not None:
            # Per-token position ids ([B, S] gather): sequence packing gives
            # each packed segment PE rows restarting at 0, so a segment sees
            # exactly the encoding its pair would see unpacked.
            pe = table[positions]
        else:
            pe = jax.lax.dynamic_slice_in_dim(
                table, position_offset, tokens.shape[-1], axis=0
            )
        x = x + pe
        return nn.Dropout(self.cfg.dropout, deterministic=deterministic)(x)


class MultiHeadAttention(nn.Module):
    """Self- or cross-attention with fused projections.

    Self-attention uses a fused QKV ``Linear(d, 3d)`` like the reference C17
    (``transformer.py:74-83``); cross-attention fuses KV (C21) but — fixing
    Q8 — reshapes each stream with its own length.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x_q: jnp.ndarray,
        x_kv: jnp.ndarray | None = None,
        mask: jnp.ndarray | None = None,
        *,
        causal: bool = False,
        kv_valid: jnp.ndarray | None = None,
        decode: bool = False,
        deterministic: bool = True,
        paged: dict | None = None,
        paged_cross: bool = False,
        sow_mem_kv: bool = False,
    ) -> jnp.ndarray:
        cfg = self.cfg
        head_dim = cfg.d_model // cfg.num_heads
        b, s_q, _ = x_q.shape

        def split_heads(t, length):
            return t.reshape(b, length, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)

        def out_proj(t):
            return nn.Dense(
                cfg.d_model,
                dtype=cfg.dtype,
                name="out",
                kernel_init=nn.with_partitioning(
                    nn.initializers.lecun_normal(), ("heads", "embed")
                ),
            )(t)

        if paged is not None:
            # Paged ragged decode (serving): ``x_q`` is one position per
            # request row ([R, 1, d]); cached K/V live in the engine's
            # shared page store and are addressed through this call's
            # block table + per-row lengths — see
            # ``ops.attention.ragged_paged_attention``. The projections
            # reuse the exact Dense modules of the padded paths ("qkv" /
            # "q" / "out"), so one set of params serves both modes.
            if paged_cross:
                # Cross-attention over prefilled memory pages; K/V were
                # projected once at prefill (sow_mem_kv below) and
                # scattered into the page store.
                q = _dense(cfg.d_model, cfg, "q", "heads")(x_q)
                ctx = ragged_paged_attention(
                    q[:, 0].reshape(b, cfg.num_heads, head_dim),
                    paged["k_pages"], paged["v_pages"],
                    paged["table"], paged["length"],
                    k_scale=paged.get("k_scale"),
                    v_scale=paged.get("v_scale"),
                )
            else:
                # Self-attention: project this step's Q/K/V, attend the
                # cached pages plus the current position (the causal
                # diagonal), and sow the new K/V so the caller can
                # scatter them into the page store after the step.
                qkv = _dense(3 * cfg.d_model, cfg, "qkv", "heads")(x_q)
                q, k, v = jnp.split(qkv, 3, axis=-1)
                self.sow("paged", "k_new", k[:, 0])
                self.sow("paged", "v_new", v[:, 0])
                ctx = ragged_paged_attention(
                    q[:, 0].reshape(b, cfg.num_heads, head_dim),
                    paged["k_pages"], paged["v_pages"],
                    paged["table"], paged["length"],
                    k_scale=paged.get("k_scale"),
                    v_scale=paged.get("v_scale"),
                    cur_k=k[:, 0], cur_v=v[:, 0],
                )
            return out_proj(ctx.reshape(b, 1, cfg.d_model))

        if x_kv is None:
            qkv = _dense(3 * cfg.d_model, cfg, "qkv", "heads")(x_q)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            s_kv = s_q
        elif decode:
            # Cross-attention decode: the encoder memory is fixed for the
            # whole generation, so its K/V projection is done once — on the
            # cache-priming call — and reused from the cache every step
            # (one [S_src, d]×[d, 2d] matmul per sequence, not per token).
            s_kv = x_kv.shape[1]
            q = _dense(cfg.d_model, cfg, "q", "heads")(x_q)
            if not self.has_variable("cache", "cached_mem_key"):
                kv = _dense(2 * cfg.d_model, cfg, "kv", "heads")(x_kv)
                k, v = jnp.split(kv, 2, axis=-1)
                self.variable("cache", "cached_mem_key", lambda: k)
                self.variable("cache", "cached_mem_value", lambda: v)
            else:
                # The "kv" Dense is skipped entirely on cached steps; all
                # submodules here carry explicit names so the module tree
                # stays stable regardless.
                k = self.variable("cache", "cached_mem_key", None).value
                v = self.variable("cache", "cached_mem_value", None).value
        else:
            s_kv = x_kv.shape[1]
            kv = _dense(2 * cfg.d_model, cfg, "kv", "heads")(x_kv)
            k, v = jnp.split(kv, 2, axis=-1)
            q = _dense(cfg.d_model, cfg, "q", "heads")(x_q)
            if sow_mem_kv:
                # Paged prefill: expose the memory K/V projections so the
                # serving runtime can scatter them into the page store —
                # the once-per-sequence cross-attention projection that
                # the flax decode cache otherwise keeps internal.
                self.sow("paged", "k_mem", k)
                self.sow("paged", "v_mem", v)

        if decode and x_kv is None:
            # Incremental decoding: append this step's K/V (one position per
            # call) to the cache and attend over everything written so far —
            # O(1) projection work per generated token instead of
            # re-projecting the whole prefix (the flax decode-cache pattern).
            is_initialized = self.has_variable("cache", "cached_key")
            cached_k = self.variable(
                "cache", "cached_key",
                jnp.zeros, (b, cfg.max_len, cfg.d_model), k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value",
                jnp.zeros, (b, cfg.max_len, cfg.d_model), v.dtype,
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            if not is_initialized:
                # Cache-shape init trace: K/V are this call's (length-1)
                # projections; any caller-passed full-width validity mask
                # does not apply to them.
                kv_valid = None
            else:
                idx = cache_index.value
                cached_k.value = jax.lax.dynamic_update_slice_in_dim(
                    cached_k.value, k, idx, axis=1
                )
                cached_v.value = jax.lax.dynamic_update_slice_in_dim(
                    cached_v.value, v, idx, axis=1
                )
                cache_index.value = idx + s_q
                k, v = cached_k.value, cached_v.value
                s_kv = cfg.max_len
                # Only the filled prefix is attendable (causality within the
                # written positions is implied by generation order); a
                # caller-provided kv_valid further masks positions whose
                # token is pad — matching the naive decoder's trg_valid.
                prefix = jnp.broadcast_to(
                    jnp.arange(cfg.max_len) < idx + s_q, (b, cfg.max_len)
                )
                kv_valid = prefix if kv_valid is None else prefix & kv_valid
                causal = False

        # Structured (causal/kv_valid) masks stream through the Pallas flash
        # kernel on TPU; a dense mask falls back to the fused-XLA path.
        out = dot_product_attention(
            split_heads(q, s_q),
            split_heads(k, s_kv),
            split_heads(v, s_kv),
            mask,
            causal=causal,
            kv_valid=kv_valid,
        )
        out = out.transpose(0, 2, 1, 3).reshape(b, s_q, cfg.d_model)
        return out_proj(out)


class FeedForward(nn.Module):
    """Position-wise FFN (C19, ``transformer.py:104-117``):
    Dense(ffn) → ReLU → Dropout → Dense(d)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, deterministic: bool = True):
        cfg = self.cfg
        h = _dense(cfg.ffn_hidden, cfg, "up", "mlp")(x)
        h = nn.relu(h)
        h = nn.Dropout(cfg.dropout, deterministic=deterministic)(h)
        return nn.Dense(
            cfg.d_model,
            dtype=cfg.dtype,
            name="down",
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("mlp", "embed")
            ),
        )(h)


def _make_ffn(cfg: TransformerConfig, name: str):
    """Dense FFN, or the switch-routed MoE variant when cfg.moe_experts > 0."""
    if cfg.moe_experts > 0:
        from machine_learning_apache_spark_tpu.models.moe import MoEFeedForward

        return MoEFeedForward(
            d_model=cfg.d_model,
            ffn_hidden=cfg.ffn_hidden,
            num_experts=cfg.moe_experts,
            capacity_factor=cfg.moe_capacity_factor,
            dropout=cfg.dropout,
            dtype=cfg.dtype,
            name=name,
        )
    return FeedForward(cfg, name=name)


class EncoderLayer(nn.Module):
    """Post-LN residual block (C20, ``transformer.py:120-139``)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self, x, mask=None, kv_valid=None, deterministic: bool = True,
        token_valid=None,
    ):
        # ``deterministic`` is positional-friendly: nn.remat marks it static
        # by argnum (keyword-only args cannot be static under jax.checkpoint).
        drop = nn.Dropout(self.cfg.dropout, deterministic=deterministic)
        attn = MultiHeadAttention(self.cfg, name="self_attn")(
            x, mask=mask, kv_valid=kv_valid, deterministic=deterministic
        )
        x = nn.LayerNorm(dtype=self.cfg.dtype, name="ln1")(x + drop(attn))
        ffn_kw = (
            # token_valid (always derived from the tokens, independent of
            # any attention-mask override) excludes pad positions from MoE
            # routing — capacity slots and aux statistics alike.
            {"valid": token_valid if token_valid is not None else kv_valid}
            if self.cfg.moe_experts > 0
            else {}
        )
        ffn = _make_ffn(self.cfg, "ffn")(
            x, deterministic=deterministic, **ffn_kw
        )
        return nn.LayerNorm(dtype=self.cfg.dtype, name="ln2")(x + drop(ffn))


class Encoder(nn.Module):
    """Embedding + layer stack (C20's ``Encoder``, ``transformer.py:149-166``)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        src_tokens,
        src_mask=None,
        src_valid=None,
        *,
        deterministic: bool = True,
        positions=None,
    ):
        x = SentenceEmbedding(self.cfg.src_vocab_size, self.cfg, name="embed")(
            src_tokens, deterministic=deterministic, positions=positions
        )
        # MoE pad exclusion must not depend on the attention-mask override:
        # derive token validity from the tokens themselves.
        token_valid = (
            src_tokens != self.cfg.pad_id if self.cfg.moe_experts > 0 else None
        )
        # static_argnums counts self at 0; deterministic is arg 4.
        layer_cls = (
            nn.remat(EncoderLayer, static_argnums=(4,))
            if self.cfg.remat
            else EncoderLayer
        )
        for i in range(self.cfg.num_layers):
            x = layer_cls(self.cfg, name=f"layer_{i}")(
                x, src_mask, src_valid, deterministic, token_valid
            )
        return x


class DecoderLayer(nn.Module):
    """Self-attn + cross-attn + FFN, each post-LN residual (C22,
    ``transformer.py:194-224``)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        y,
        memory,
        self_mask=None,
        cross_mask=None,
        trg_valid=None,
        memory_valid=None,
        self_causal: bool = False,
        decode: bool = False,
        deterministic: bool = True,
        token_valid=None,
        paged_self: dict | None = None,
        paged_mem: dict | None = None,
        sow_mem_kv: bool = False,
    ):
        # Flags are plain positional-friendly bools so nn.remat can mark
        # them static by argnum (7, 8, 9; self counts at 0). The paged_*
        # kwargs are the serving decode path (never rematerialized).
        drop = nn.Dropout(self.cfg.dropout, deterministic=deterministic)
        attn = MultiHeadAttention(self.cfg, name="self_attn")(
            y,
            mask=self_mask,
            causal=self_causal,
            kv_valid=trg_valid,
            decode=decode,
            deterministic=deterministic,
            paged=paged_self,
        )
        y = nn.LayerNorm(dtype=self.cfg.dtype, name="ln1")(y + drop(attn))
        cross = MultiHeadAttention(self.cfg, name="cross_attn")(
            y,
            memory,
            mask=cross_mask,
            kv_valid=memory_valid,
            decode=decode,
            deterministic=deterministic,
            paged=paged_mem,
            paged_cross=paged_mem is not None,
            sow_mem_kv=sow_mem_kv,
        )
        y = nn.LayerNorm(dtype=self.cfg.dtype, name="ln2")(y + drop(cross))
        ffn_kw = (
            # token_valid is derived from the tokens regardless of mask
            # overrides; it matches y's positions only outside decode (a
            # decode step feeds [B, 1] tokens while validity spans the
            # cache), so the decode path routes its single real token.
            {"valid": None if (decode or paged_self is not None) else (
                token_valid if token_valid is not None else trg_valid
            )}
            if self.cfg.moe_experts > 0
            else {}
        )
        ffn = _make_ffn(self.cfg, "ffn")(
            y, deterministic=deterministic, **ffn_kw
        )
        return nn.LayerNorm(dtype=self.cfg.dtype, name="ln3")(y + drop(ffn))


class Decoder(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self,
        trg_tokens,
        memory,
        self_mask=None,
        cross_mask=None,
        trg_valid=None,
        memory_valid=None,
        *,
        self_causal: bool = False,
        decode: bool = False,
        position_offset: jnp.ndarray | int = 0,
        positions=None,
        deterministic: bool = True,
        paged: dict | None = None,
        sow_mem_kv: bool = False,
    ):
        y = SentenceEmbedding(self.cfg.trg_vocab_size, self.cfg, name="embed")(
            trg_tokens,
            deterministic=deterministic,
            position_offset=position_offset,
            positions=positions,
        )
        # MoE pad exclusion, independent of any attention-mask override.
        token_valid = (
            trg_tokens != self.cfg.pad_id if self.cfg.moe_experts > 0 else None
        )
        # Remat only on the training path: the decode cache is a mutable
        # variable collection, which jax.checkpoint cannot rewind (and the
        # paged/sow serving paths use keyword args remat can't thread).
        layer_cls = (
            nn.remat(DecoderLayer, static_argnums=(7, 8, 9))
            if self.cfg.remat and not decode and paged is None
            and not sow_mem_kv
            else DecoderLayer
        )
        for i in range(self.cfg.num_layers):
            layer_kw = {}
            if paged is not None:
                # Each layer owns one [2, num_pages, page, d] plane of
                # each page store. Self- and cross-attention address
                # *separate* stores: the self store is the decode loop's
                # scan carry (small — grows with generated tokens), the
                # mem store holds prompt cross-KV and is read-only during
                # decode, so it never rides a carry or gets copied.
                layer_kw = dict(
                    paged_self=dict(
                        k_pages=paged["self_pages"][i, 0],
                        v_pages=paged["self_pages"][i, 1],
                        table=paged["self_table"],
                        length=paged["self_len"],
                    ),
                    paged_mem=dict(
                        k_pages=paged["mem_pages"][i, 0],
                        v_pages=paged["mem_pages"][i, 1],
                        table=paged["mem_table"],
                        length=paged["mem_len"],
                    ),
                )
                # Quantized stores ship per-slot dequantization scales
                # ([layers, 2, num_pages, page]) alongside the int8
                # payload; each attention site gets its layer's k/v plane.
                if paged.get("self_scales") is not None:
                    layer_kw["paged_self"]["k_scale"] = (
                        paged["self_scales"][i, 0]
                    )
                    layer_kw["paged_self"]["v_scale"] = (
                        paged["self_scales"][i, 1]
                    )
                if paged.get("mem_scales") is not None:
                    layer_kw["paged_mem"]["k_scale"] = (
                        paged["mem_scales"][i, 0]
                    )
                    layer_kw["paged_mem"]["v_scale"] = (
                        paged["mem_scales"][i, 1]
                    )
            if sow_mem_kv:
                layer_kw["sow_mem_kv"] = True
            y = layer_cls(self.cfg, name=f"layer_{i}")(
                y,
                memory,
                self_mask,
                cross_mask,
                trg_valid,
                memory_valid,
                self_causal,
                decode,
                deterministic,
                token_valid,
                **layer_kw,
            )
        return y


class Transformer(nn.Module):
    """Encoder + Decoder + LM head (C23, ``transformer.py:255-284``).

    ``__call__(src_tokens, trg_tokens)`` builds the three masks from the pad
    id — src self-attn padding, trg causal∧padding, cross (trg queries over
    src keys) — matching the MT driver's mask plumbing
    (``pytorch_machine_translator.py:164-177``) but with the correct
    semantics; explicit masks may be passed to override.
    """

    cfg: TransformerConfig

    def setup(self):
        self.encoder = Encoder(self.cfg)
        self.decoder = Decoder(self.cfg)
        # LM head: d_model → trg vocab (+ TP padding), the reference's
        # Linear(512, |de|) (``transformer.py:271,283``), vocab axis
        # model-sharded under TP.
        self.lm_head = nn.Dense(
            self.cfg.trg_vocab_size + self.cfg.logit_pad,
            dtype=self.cfg.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
        )

    def _logits(self, y: jnp.ndarray) -> jnp.ndarray:
        """LM head with the TP vocab padding sliced off."""
        logits = self.lm_head(y)
        if self.cfg.logit_pad:
            logits = logits[..., : self.cfg.trg_vocab_size]
        return logits

    def __call__(
        self,
        src_tokens: jnp.ndarray,
        trg_tokens: jnp.ndarray,
        src_mask: jnp.ndarray | None = None,
        trg_mask: jnp.ndarray | None = None,
        cross_mask: jnp.ndarray | None = None,
        *,
        src_positions: jnp.ndarray | None = None,
        trg_positions: jnp.ndarray | None = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        pad = self.cfg.pad_id
        # Default masks stay *structured* — per-key validity vectors plus a
        # causal flag — so TPU runs stream them through the flash kernel
        # without materializing [B, Sq, Sk] (an explicit dense mask override
        # still takes the fused-XLA path). Sequence packing
        # (``data.packing``) overrides all three masks with block-diagonal
        # segment masks and supplies per-token ``*_positions``.
        src_valid = (src_tokens != pad) if src_mask is None else None
        trg_valid = (trg_tokens != pad) if trg_mask is None else None
        # Cross-attention defaults to masking padded *source* keys whenever
        # the caller did not override cross_mask — independent of whether
        # src_mask was overridden (each attention site keeps its own default).
        memory_valid = (src_tokens != pad) if cross_mask is None else None
        memory = self.encoder(
            src_tokens, src_mask, src_valid, deterministic=deterministic,
            positions=src_positions,
        )
        y = self.decoder(
            trg_tokens,
            memory,
            trg_mask,
            cross_mask,
            trg_valid,
            memory_valid,
            self_causal=trg_mask is None,
            positions=trg_positions,
            deterministic=deterministic,
        )
        return self._logits(y)

    def encode(self, src_tokens, *, deterministic: bool = True):
        return self.encoder(
            src_tokens,
            None,
            src_tokens != self.cfg.pad_id,
            deterministic=deterministic,
        )

    def decode_logits(self, trg_tokens, memory, src_valid):
        """One decoder pass → vocab logits, for the generation loop (no
        dropout; causal + padding via structured masks)."""
        y = self.decoder(
            trg_tokens,
            memory,
            None,
            None,
            trg_tokens != self.cfg.pad_id,
            src_valid,
            self_causal=True,
            deterministic=True,
        )
        return self._logits(y)

    def decode_step(self, token, memory, src_valid, position, trg_valid=None):
        """One incremental step: ``token`` is ``[B, 1]``, self-attention
        K/V come from the mutable ``cache`` collection — O(1) projection
        work per generated token (the KV-cache decoder). ``trg_valid``
        ([B, max_len]) marks which written cache positions hold real (non-
        pad) tokens, mirroring the naive decoder's padding mask."""
        y = self.decoder(
            token,
            memory,
            None,
            None,
            trg_valid,
            src_valid,
            decode=True,
            position_offset=position,
            deterministic=True,
        )
        return self._logits(y)

    def prefill_paged(self, src_tokens):
        """Paged-serving prefill: encode the prompt and project every
        decoder layer's cross-attention K/V over the memory — sown into
        the ``"paged"`` collection (``decoder/layer_i/cross_attn/
        k_mem|v_mem``, each ``[B, S_src, d]``) for the serving runtime to
        scatter into its page store. This is the once-per-sequence work
        the flax decode cache does on its priming call, surfaced so the
        cached K/V can outlive the request (prefix sharing)."""
        src_valid = src_tokens != self.cfg.pad_id
        memory = self.encoder(
            src_tokens, None, src_valid, deterministic=True
        )
        dummy = jnp.full((src_tokens.shape[0], 1), 1, jnp.int32)
        self.decoder(
            dummy, memory, None, None, None, src_valid,
            sow_mem_kv=True, deterministic=True,
        )
        return memory

    def decode_step_paged(
        self, token, self_pages, mem_pages, self_table, self_len,
        mem_table, mem_len, positions,
        self_scales=None, mem_scales=None,
    ):
        """One ragged decode step over the paged KV stores: ``token`` is
        ``[R, 1]`` (one position per request row); ``self_pages`` and
        ``mem_pages`` are ``[layers, 2, num_pages, page, d]`` stores —
        the *self* store holds generated-token K/V (small, mutated every
        step: the decode loop's scan carry), the *mem* store holds the
        prompts' cross-attention K/V (written at prefill, read-only here,
        so the launch program never copies it). The tables/lengths
        address each row's pages in its store, and ``positions``
        (``[R, 1]``) carries each row's own PE index — rows at different
        depths of generation share one program. The step's new
        self-attention K/V are sown into the ``"paged"`` collection
        (``decoder/layer_i/self_attn/k_new|v_new``) for the caller to
        scatter at each row's cursor. Quantized stores (int8 payload)
        pass their per-slot dequantization scales as ``self_scales`` /
        ``mem_scales`` (``[layers, 2, num_pages, page]`` float32);
        ``None`` means that store is full-precision."""
        y = self.decoder(
            token,
            None,
            None,
            None,
            None,
            None,
            paged=dict(
                self_pages=self_pages,
                mem_pages=mem_pages,
                self_table=self_table,
                self_len=self_len,
                mem_table=mem_table,
                mem_len=mem_len,
                self_scales=self_scales,
                mem_scales=mem_scales,
            ),
            positions=positions,
            deterministic=True,
        )
        return self._logits(y)


def greedy_translate(
    model: "Transformer",
    params,
    src_tokens: jnp.ndarray,
    *,
    max_new_tokens: int | None = None,
    sos_id: int = 1,
    eos_id: int = 2,
) -> jnp.ndarray:
    """Greedy decoding for the MT model — the inference path the reference
    never ships (it trains and discards, quirk Q7 / SURVEY.md §5).

    Re-runs the full decoder per emitted token over a fixed-width buffer
    (static shapes; one compile). O(L²) decoder work — the simple faithful
    path; a KV-cache incremental decoder is the documented follow-up.
    Generates exactly ``max_new_tokens`` tokens (default: ``cfg.max_len - 1``)
    after the leading ``sos``; returns ``[B, max_new_tokens + 1]`` int32 ids,
    rows padded after their ``eos``.
    """
    cfg = model.cfg
    pad = cfg.pad_id
    if max_new_tokens is None:
        max_new_tokens = cfg.max_len - 1
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    length = max_new_tokens + 1  # + the sos slot; PE table grows statically
    src_valid = src_tokens != pad
    memory = model.apply(
        {"params": params}, src_tokens, method=Transformer.encode
    )

    ys = jnp.full((src_tokens.shape[0], length), pad, jnp.int32)
    ys = ys.at[:, 0].set(sos_id)
    finished = jnp.zeros(src_tokens.shape[0], bool)

    def step(carry, t):
        ys, finished = carry
        logits = model.apply(
            {"params": params},
            ys,
            memory,
            src_valid,
            method=Transformer.decode_logits,
        )
        nxt = jnp.argmax(logits[:, t, :], axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, pad, nxt)
        finished = finished | (nxt == eos_id)
        ys = jax.lax.dynamic_update_index_in_dim(ys, nxt, t + 1, axis=1)
        return (ys, finished), None

    (ys, _), _ = jax.lax.scan(step, (ys, finished), jnp.arange(length - 1))
    return ys


def _prime_decode_cache(decode_model, params, memory, src_valid, gen_len, sos_id):
    """Cache-priming call shared by the cached decoders: creates the zeroed
    self-attention K/V buffers AND projects the encoder memory's
    cross-attention K/V once, storing them in the cache. The priming logits
    are discarded; the init trace writes nothing into the self-attention
    cache, so the first real step recomputes sos with identical semantics.
    """
    rows = memory.shape[0]
    _, primed = decode_model.apply(
        {"params": params},
        jnp.full((rows, 1), sos_id, jnp.int32),
        memory,
        src_valid,
        jnp.zeros((), jnp.int32),
        jnp.ones((rows, gen_len), bool),
        method=Transformer.decode_step,
        mutable=["cache"],
    )
    return primed["cache"]


def _validate_max_new_tokens(max_new_tokens, cfg):
    if max_new_tokens is None:
        return cfg.max_len - 1
    if not 1 <= max_new_tokens <= cfg.max_len - 1:
        raise ValueError(
            f"max_new_tokens must be in [1, {cfg.max_len - 1}], got "
            f"{max_new_tokens}"
        )
    return max_new_tokens


def beam_translate(
    model: "Transformer",
    params,
    src_tokens: jnp.ndarray,
    *,
    beam_size: int = 4,
    max_new_tokens: int | None = None,
    length_penalty: float = 0.6,
    sos_id: int = 1,
    eos_id: int = 2,
) -> jnp.ndarray:
    """KV-cache beam search — the inference path the reference never ships,
    taken past greedy.

    TPU-first shape discipline: beams are flat-batched (``B·K`` rows share
    one decode cache), every step is one fused program inside a single
    ``lax.scan`` (top-k over ``K·V``, beam reorder via gather, cache rows
    gathered alongside), and nothing is data-dependently shaped. Finished
    beams extend only with ``pad`` at zero cost; hypothesis selection uses
    the GNMT length penalty ``((5+L)/6)^alpha`` (``length_penalty=0`` scores
    raw log-probs; ``beam_size=1`` reproduces greedy decoding exactly).

    Returns ``[B, max_new_tokens + 1]`` int32 ids (leading ``sos``, rows
    padded after their ``eos``) — the ``greedy_translate`` contract.
    """
    cfg = model.cfg
    pad = cfg.pad_id
    max_new_tokens = _validate_max_new_tokens(max_new_tokens, cfg)
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    b = src_tokens.shape[0]
    k = beam_size
    gen_len = max_new_tokens + 1
    vocab = cfg.trg_vocab_size

    src_valid = src_tokens != pad
    memory = model.apply(
        {"params": params}, src_tokens, method=Transformer.encode
    )
    # Tile encoder outputs to the flat beam batch: row b*k + j is beam j of
    # sentence b.
    memory = jnp.repeat(memory, k, axis=0)
    src_valid_t = jnp.repeat(src_valid, k, axis=0)

    decode_model = Transformer(dataclasses.replace(cfg, max_len=gen_len))
    cache = _prime_decode_cache(
        decode_model, params, memory, src_valid_t, gen_len, sos_id
    )

    ys = jnp.full((b, k, gen_len), pad, jnp.int32)
    ys = ys.at[:, :, 0].set(sos_id)
    scores = jnp.zeros((b, k), jnp.float32)
    finished = jnp.zeros((b, k), bool)
    lengths = jnp.zeros((b, k), jnp.int32)  # generated tokens incl. eos
    # GNMT-style completed-hypothesis set (capacity 1 — the best): a
    # finished beam can be evicted from the live set by raw-score top-k, so
    # its penalized score/tokens are banked the step it finishes.
    best_score = jnp.full((b,), NEG_INF, jnp.float32)
    best_ys = jnp.full((b, gen_len), pad, jnp.int32)

    def _penalize(score, length):
        return score / ((5.0 + length.astype(jnp.float32)) / 6.0) ** length_penalty

    def reorder_cache(tree, beam_idx):
        def gather(path, leaf):
            # Cross-attention memory K/V (cached_mem_*) are identical across
            # beams of one sentence (tiled from one encode) — gathering them
            # would be pure HBM traffic; scalars (cache_index) likewise ride.
            if any("cached_mem" in str(p) for p in path):
                return leaf
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == b * k:
                x = leaf.reshape(b, k, *leaf.shape[1:])
                idx = beam_idx.reshape(b, k, *([1] * (leaf.ndim - 1)))
                x = jnp.take_along_axis(x, idx, axis=1)
                return x.reshape(b * k, *leaf.shape[1:])
            return leaf

        return jax.tree_util.tree_map_with_path(gather, tree)

    def step(carry, t):
        ys, scores, finished, lengths, best_score, best_ys, cache = carry
        token = jax.lax.dynamic_slice_in_dim(ys, t, 1, axis=2)  # [b,k,1]
        logits, updated = decode_model.apply(
            {"params": params, "cache": cache},
            token.reshape(b * k, 1),
            memory,
            src_valid_t,
            t,
            (ys != pad).reshape(b * k, gen_len),
            method=Transformer.decode_step,
            mutable=["cache"],
        )
        logp = jax.nn.log_softmax(
            logits[:, 0, :].astype(jnp.float32), axis=-1
        ).reshape(b, k, vocab)
        # Finished beams extend only with pad, at zero cost.
        pad_only = jnp.full((vocab,), NEG_INF).at[pad].set(0.0)
        logp = jnp.where(finished[:, :, None], pad_only, logp)
        total = scores[:, :, None] + logp  # [b, k, vocab]
        # Step 0: all beams are identical copies of sos — search beam 0 only,
        # or top-k would return k copies of the same hypothesis.
        total = jnp.where(
            (t == 0) & (jnp.arange(k)[None, :, None] > 0), NEG_INF, total
        )
        new_scores, flat_idx = jax.lax.top_k(total.reshape(b, k * vocab), k)
        beam_idx = flat_idx // vocab  # [b, k] which parent beam
        token = (flat_idx % vocab).astype(jnp.int32)

        gathered = lambda x: jnp.take_along_axis(x, beam_idx, axis=1)
        was_finished = gathered(finished)
        ys = jnp.take_along_axis(ys, beam_idx[:, :, None], axis=1)
        ys = jax.lax.dynamic_update_slice_in_dim(
            ys, token[:, :, None], t + 1, axis=2
        )
        lengths = gathered(lengths) + (~was_finished).astype(jnp.int32)
        newly_finished = ~was_finished & (token == eos_id)
        finished = was_finished | (token == eos_id)
        # Bank the best newly finished hypothesis before top-k can evict it.
        cand = jnp.where(newly_finished, _penalize(new_scores, lengths), NEG_INF)
        cand_beam = jnp.argmax(cand, axis=1)  # [b]
        cand_score = jnp.take_along_axis(cand, cand_beam[:, None], axis=1)[:, 0]
        cand_ys = jnp.take_along_axis(
            ys, cand_beam[:, None, None], axis=1
        )[:, 0, :]
        better = cand_score > best_score
        best_score = jnp.where(better, cand_score, best_score)
        best_ys = jnp.where(better[:, None], cand_ys, best_ys)
        cache = reorder_cache(updated["cache"], beam_idx)
        return (
            ys, new_scores, finished, lengths, best_score, best_ys, cache
        ), None

    (ys, scores, finished, lengths, best_score, best_ys, _), _ = jax.lax.scan(
        step,
        (ys, scores, finished, lengths, best_score, best_ys, cache),
        jnp.arange(max_new_tokens),
    )

    # Selection: the banked best finished hypothesis wins when one exists
    # (every finished beam was banked the step it finished, so none is ever
    # lost to eviction); otherwise the best live beam by penalized score.
    live_best = jnp.argmax(_penalize(scores, lengths), axis=1)  # [b]
    live_ys = jnp.take_along_axis(ys, live_best[:, None, None], axis=1)[:, 0, :]
    use_banked = best_score > NEG_INF * 0.5
    return jnp.where(use_banked[:, None], best_ys, live_ys)


def _cached_decode(
    model: "Transformer",
    params,
    src_tokens: jnp.ndarray,
    select_next,
    *,
    max_new_tokens: int | None,
    sos_id: int,
    eos_id: int,
) -> jnp.ndarray:
    """Shared KV-cache decode loop: encode once, prime the cache, then scan
    one-token decoder steps; ``select_next(logits[B, V], t) -> [B] int32``
    is the only policy difference between the greedy and sampling decoders.

    Each step runs the decoder stack on only the new token, appending its
    self-attention K/V to a mutable cache — O(1) decoder work per token vs
    the O(L) full re-decode of ``greedy_translate``. Cross-attention K/V
    over the encoder memory are projected once, on the priming call.
    """
    cfg = model.cfg
    pad = cfg.pad_id
    max_new_tokens = _validate_max_new_tokens(max_new_tokens, cfg)
    b = src_tokens.shape[0]
    src_valid = src_tokens != pad
    memory = model.apply(
        {"params": params}, src_tokens, method=Transformer.encode
    )
    # Cache buffers sized to the generation length, not cfg.max_len — the
    # params are max_len-independent, so a config-shrunk twin of the model
    # right-sizes every layer's K/V cache (and each step's attention span).
    gen_len = max_new_tokens + 1
    decode_model = Transformer(dataclasses.replace(cfg, max_len=gen_len))
    cache = _prime_decode_cache(
        decode_model, params, memory, src_valid, gen_len, sos_id
    )

    ys = jnp.full((b, gen_len), pad, jnp.int32)
    ys = ys.at[:, 0].set(sos_id)
    finished = jnp.zeros(b, bool)

    def step(carry, t):
        ys, finished, cache = carry
        token = jax.lax.dynamic_slice_in_dim(ys, t, 1, axis=1)
        logits, updated = decode_model.apply(
            {"params": params, "cache": cache},
            token,
            memory,
            src_valid,
            t,
            ys != pad,  # pad tokens in the prefix stay unattendable (naive parity)
            method=Transformer.decode_step,
            mutable=["cache"],
        )
        nxt = select_next(logits[:, 0, :], t).astype(jnp.int32)
        nxt = jnp.where(finished, pad, nxt)
        finished = finished | (nxt == eos_id)
        ys = jax.lax.dynamic_update_index_in_dim(ys, nxt, t + 1, axis=1)
        return (ys, finished, updated["cache"]), None

    (ys, _, _), _ = jax.lax.scan(
        step, (ys, finished, cache), jnp.arange(max_new_tokens)
    )
    return ys


def greedy_translate_cached(
    model: "Transformer",
    params,
    src_tokens: jnp.ndarray,
    *,
    max_new_tokens: int | None = None,
    sos_id: int = 1,
    eos_id: int = 2,
) -> jnp.ndarray:
    """KV-cache greedy decoding — ``_cached_decode`` with an argmax policy.
    Same output contract as ``greedy_translate``."""
    return _cached_decode(
        model, params, src_tokens,
        lambda logits, t: jnp.argmax(logits, axis=-1),
        max_new_tokens=max_new_tokens, sos_id=sos_id, eos_id=eos_id,
    )


def _filter_logits(
    logits: jnp.ndarray, temperature: float, top_k: int | None, top_p: float | None
) -> jnp.ndarray:
    """Sampling filters over ``[B, V]`` logits: temperature scaling, then
    top-k truncation, then nucleus (top-p) truncation — masked-out entries
    become NEG_INF so ``jax.random.categorical`` never selects them."""
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # top_k >= vocab keeps everything (not an error — mirrors the
        # temperature-only case).
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # Keep the smallest prefix whose mass reaches top_p (the first token
        # always survives: its exclusive cumulative mass is 0 < top_p).
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive_cum < top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return logits


def sample_translate(
    model: "Transformer",
    params,
    src_tokens: jnp.ndarray,
    rng: jax.Array,
    *,
    max_new_tokens: int | None = None,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    sos_id: int = 1,
    eos_id: int = 2,
) -> jnp.ndarray:
    """Stochastic decoding with temperature / top-k / nucleus filtering —
    ``_cached_decode`` with a filtered-categorical policy (O(1) decoder work
    per token). ``temperature=0`` degrades to greedy argmax. Same output
    contract as the greedy decoders: ``[B, max_new_tokens + 1]`` int32 ids,
    ``sos``-led, rows padded after their ``eos``.
    """
    # Validate filter args eagerly and uniformly (the greedy temperature=0
    # branch must reject bad top_k/top_p exactly like the sampling branch).
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0.0:  # static: resolved at trace time
        select = lambda logits, t: jnp.argmax(logits, axis=-1)
    else:
        def select(logits, t):
            filtered = _filter_logits(logits, temperature, top_k, top_p)
            return jax.random.categorical(jax.random.fold_in(rng, t), filtered)

    return _cached_decode(
        model, params, src_tokens, select,
        max_new_tokens=max_new_tokens, sos_id=sos_id, eos_id=eos_id,
    )
