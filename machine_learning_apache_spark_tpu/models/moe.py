"""Mixture-of-experts feed-forward — expert parallelism for the zoo.

The reference has no MoE anywhere (SURVEY.md §2.3: EP "out of scope" for
parity); this module is capability headroom completing the mesh's five
axes (``parallel.mesh``: data/model/seq/pipeline/expert). Design follows
the canonical TPU MoE shape (Switch Transformer-style top-1 routing with
static capacity, one-hot einsum dispatch/combine — the Shazeer/Fedus
lineage all public TPU MoE code uses, e.g. mesh-tensorflow/flaxformer):

- **Static shapes**: every tensor has a compile-time shape. Each sequence
  is its own routing group with ``capacity = ceil(capacity_factor × seq /
  num_experts)`` slots per expert (the mesh-tf/flaxformer grouping — it
  bounds the dispatch tensor at ``cf·b·s²`` rather than ``cf·(b·s)²``);
  overflow tokens are *dropped* — their FFN output is zero and the
  surrounding residual connection carries them through unchanged (the
  standard Switch behavior, not a bug).
- **Einsum dispatch**: a boolean dispatch tensor ``D[b, s, e, c]`` gathers
  token features into per-expert buffers ``[E, B, C, d]``; the expert FFNs
  are one batched matmul pair over the leading expert dim; a weighted
  combine scatters results back. No gather/scatter ops, no dynamic shapes —
  XLA tiles everything onto the MXU.
- **Expert parallelism**: expert weights carry the logical axis ``"expert"``
  on their leading dim (→ mesh axis ``"expert"`` via
  ``parallel.tensor_parallel.DEFAULT_RULES``). Under ``pjit`` XLA partitions
  the dispatch einsum into an all-to-all-shaped exchange and each device
  runs only its experts — the scaling-book recipe, nothing hand-scheduled.
- **Load balancing**: the Switch auxiliary loss ``E · Σ_e f_e · p_e``
  (fraction-routed × mean-router-prob) is sown into the ``"losses"``
  collection; training code adds ``moe_aux_weight ×`` their mean to the task
  loss (see ``recipes.translation.make_translation_loss``).

Router numerics are float32 regardless of compute dtype (softmax over a
handful of logits is precision-critical; bf16 router probs destabilize
balancing).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense position-wise FFN.

    Input/output ``[B, S, d_model]``; interface-compatible with
    ``transformer.FeedForward`` so encoder/decoder layers swap it in behind
    a config flag.
    """

    d_model: int
    ffn_hidden: int
    num_experts: int
    capacity_factor: float = 1.25
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        valid: jnp.ndarray | None = None,
        deterministic: bool = True,
    ):
        b, s, d = x.shape
        e = self.num_experts
        # Per-SEQUENCE routing groups (the mesh-tf/flaxformer convention):
        # each batch row assigns its own capacity = ceil(cf · s / E) slots
        # per expert, so the dispatch tensor is [b, s, E, C] ~ cf·b·s² —
        # bounded by the sequence length, not (batch·seq)², which at
        # long-context scale is the difference between MBs and GBs.
        capacity = max(int(math.ceil(self.capacity_factor * s / e)), 1)

        # Pad tokens (valid=False) are excluded from routing entirely: they
        # never consume a capacity slot (which would drop real tokens at a
        # far higher rate than capacity_factor implies on padded batches)
        # and never enter the aux-loss statistics. Their FFN output is zero;
        # the surrounding residual carries them.
        if valid is not None and valid.shape != (b, s):
            raise ValueError(
                f"valid must be [batch={b}, seq={s}], got {valid.shape}"
            )
        vf = (
            valid.astype(jnp.float32)
            if valid is not None
            else jnp.ones((b, s), jnp.float32)
        )

        # -- router (float32) ------------------------------------------------
        router_kernel = self.param(
            "router",
            nn.with_partitioning(nn.initializers.lecun_normal(), ("embed", None)),
            (d, e),
        )
        logits = jnp.einsum(
            "bsd,de->bse",
            x.astype(jnp.float32),
            router_kernel.astype(jnp.float32),
        )
        probs = jax.nn.softmax(logits, axis=-1)  # [B, S, E]
        expert_idx = jnp.argmax(probs, axis=-1)  # [B, S] top-1 (Switch)
        gate = jnp.take_along_axis(probs, expert_idx[..., None], axis=-1)[..., 0]
        gate = gate * vf

        # -- capacity assignment (within each row's groups) ------------------
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32) * vf[..., None]
        # Slot within the chosen expert's buffer, in token order within the
        # row (exclusive running count of prior same-expert tokens).
        position = (jnp.cumsum(onehot, axis=1) - onehot) * onehot  # [B, S, E]
        pos_in_expert = position.sum(axis=-1).astype(jnp.int32)  # [B, S]
        keep = pos_in_expert < capacity
        gate = jnp.where(keep, gate, 0.0)

        # Dispatch tensor [B, S, E, C]: token (b, s) → (its expert, its slot).
        dispatch = (
            onehot[..., None]
            * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)[
                :, :, None, :
            ]
            * keep[..., None, None]
        )

        # -- expert FFNs (batched over the expert dim) ----------------------
        w_up = self.param(
            "w_up",
            nn.with_partitioning(
                nn.initializers.lecun_normal(), ("expert", "embed", "mlp")
            ),
            (e, d, self.ffn_hidden),
        )
        w_down = self.param(
            "w_down",
            nn.with_partitioning(
                nn.initializers.lecun_normal(), ("expert", "mlp", "embed")
            ),
            (e, self.ffn_hidden, d),
        )
        expert_in = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(self.dtype), x.astype(self.dtype)
        )
        h = nn.relu(
            jnp.einsum("ebcd,edf->ebcf", expert_in, w_up.astype(self.dtype))
        )
        h = nn.Dropout(self.dropout, deterministic=deterministic)(h)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h, w_down.astype(self.dtype))

        # -- weighted combine ------------------------------------------------
        combine = dispatch * gate[..., None, None]  # [B, S, E, C]
        out = jnp.einsum(
            "bsec,ebcd->bsd", combine.astype(self.dtype), expert_out
        )

        # -- Switch load-balancing loss -------------------------------------
        # f_e is the fraction of VALID tokens the router chose per expert
        # (pre-drop, the Switch paper's definition); p_e the mean router
        # prob over valid tokens. Drops are a consequence the loss should
        # shrink, not a term that hides imbalance by zeroing overflow.
        n_valid = jnp.maximum(vf.sum(), 1.0)
        frac_routed = onehot.sum(axis=(0, 1)) / n_valid  # f_e
        mean_prob = (probs * vf[..., None]).sum(axis=(0, 1)) / n_valid  # p_e
        aux = e * jnp.sum(frac_routed * mean_prob)
        self.sow("losses", "moe_aux", aux)

        return out
