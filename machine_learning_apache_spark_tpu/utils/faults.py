"""Deterministic fault injection — the chaos the robustness layer is tested by.

Fault tolerance that has never seen a fault is a comment, not a feature.
This module gives every crash-containment path in the repo (gang
restart, checkpoint resume, serving quarantine, heartbeat detection) a
deterministic trigger: a *plan* of faults, each pinned to an exact site
and coordinate ("crash rank 1 at train step 5", "raise in decode batch
2", "stall rank 0's heartbeats at step 3"), installed either
programmatically (tests) or through the environment (spawned gang
workers, the fault drill).

Grammar (``MLSPARK_FAULTS``, semicolon-separated)::

    action@site:key=value,key=value;action@site:...

    crash@train_step:rank=1,step=5     # os._exit(23) — a hard kill
    raise@decode_batch:batch=2         # raise FaultInjected in the engine
    stall@train_step:rank=0,step=3     # suspend heartbeats + hang

Sites are the instrumented ``maybe_fault(site, ...)`` call points:
``train_step`` (train.loop, per optimizer step) and ``decode_batch``
(serving.engine, per formed batch). ``rank`` matches
``MLSPARK_PROCESS_ID`` (absent -> matches any process); ``world``
matches ``MLSPARK_NUM_PROCESSES`` — the elastic-drill lever: a plan
like ``crash@train_step:world=8,rank=7,...;crash@train_step:world=7,
rank=6,...`` kills one rank per world size, so each shrunken gang
meets exactly its own fault and the drill walks 8 -> 7 -> 6
deterministically.

**Wire faults.** A second action family targets one HTTP exchange on
the fleet data plane instead of a process::

    delay@wire:rank=1,ms=500          # hold the exchange 500ms (straggler)
    blackhole@wire:rank=0,req=3       # swallow the request, never respond
    torn@wire:rank=0,req=2            # full Content-Length, half a body
    corrupt@wire:rank=1,req=5         # right length, unparseable JSON
    drip@wire:rank=0,req=1,ms=2000    # trickle the body out over 2s

Wire specs live only at the ``wire`` site and are *queried* (via
:func:`wire_fault`) by ``ReplicaServer``'s request handler, which
implements the behavior itself — ``maybe_fault`` never executes them.
Coordinates are deterministic: ``rank`` is the replica's rank, ``req``
the zero-based ordinal of the exchange on that server (absent = every
exchange). ``ms`` is the action's magnitude (delay/drip duration).
``sticky=1`` exempts a spec from one-shot semantics — the persistent
slow replica a straggler-hedging drill needs; the marker file still
records the first firing as proof.

**One-shot semantics.** A fault fires once. In-process that's a set of
fired keys; across process restarts (the gang-retry case — the retried
worker re-executes the same step numbers) it's a marker file under
``MLSPARK_FAULTS_DIR``, written *before* the action so even an
``os._exit`` can't re-arm itself. Without a marker dir, ``crash``/
``stall`` faults would re-fire on every gang attempt and no retry could
ever succeed — ``FaultPlan.from_env`` therefore logs a warning when a
crash/stall plan has no marker dir.

The hot-path cost when no plan is installed is one global ``is None``
check in ``maybe_fault``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time


def _log():
    # Lazy: utils.logging imports jax (rank gating), and this module must
    # stay stdlib-importable — the runner's heartbeat thread polls
    # heartbeats_suspended() before the worker's JAX platform is settled.
    from machine_learning_apache_spark_tpu.utils.logging import get_logger

    return get_logger(__name__)


ENV_PLAN = "MLSPARK_FAULTS"
ENV_MARKER_DIR = "MLSPARK_FAULTS_DIR"

_ACTIONS = ("crash", "raise", "stall")
WIRE_ACTIONS = ("delay", "blackhole", "torn", "corrupt", "drip")
WIRE_SITE = "wire"


class FaultInjected(RuntimeError):
    """An injected failure (the ``raise`` action) — never raised by real
    code paths, so tests can assert provenance."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned fault: fire ``action`` at ``site`` when every given
    coordinate matches (``None`` = wildcard)."""

    action: str
    site: str
    rank: int | None = None
    step: int | None = None
    batch: int | None = None
    world: int | None = None
    req: int | None = None
    ms: int = 0
    sticky: int = 0
    exit_code: int = 23

    @property
    def key(self) -> str:
        """Stable marker-file name for one-shot bookkeeping."""
        return (
            f"{self.action}_{self.site}"
            f"_r{'any' if self.rank is None else self.rank}"
            f"_s{'any' if self.step is None else self.step}"
            f"_b{'any' if self.batch is None else self.batch}"
            + ("" if self.world is None else f"_w{self.world}")
            + ("" if self.req is None else f"_q{self.req}")
            + ("" if not self.ms else f"_m{self.ms}")
        )

    def matches(self, site: str, rank: int | None, step: int | None,
                batch: int | None, world: int | None = None,
                req: int | None = None) -> bool:
        if self.site != site:
            return False
        for want, got in (
            (self.rank, rank), (self.step, step), (self.batch, batch),
            (self.world, world), (self.req, req),
        ):
            if want is not None and want != got:
                return False
        return True


class FaultPlan:
    """An installed set of ``FaultSpec``s with one-shot bookkeeping."""

    def __init__(self, specs: list[FaultSpec], *, marker_dir: str | None = None):
        self.specs = list(specs)
        self.marker_dir = marker_dir
        self._fired: set[str] = set()
        self._lock = threading.Lock()

    # -- parsing -------------------------------------------------------------
    @classmethod
    def from_spec(cls, text: str, *, marker_dir: str | None = None) -> "FaultPlan":
        specs = []
        for entry in filter(None, (e.strip() for e in text.split(";"))):
            action, _, rest = entry.partition("@")
            if action not in _ACTIONS and action not in WIRE_ACTIONS:
                raise ValueError(
                    f"unknown fault action {action!r} in {entry!r} "
                    f"(expected one of {_ACTIONS + WIRE_ACTIONS})"
                )
            site, _, kvs = rest.partition(":")
            if not site:
                raise ValueError(f"fault entry {entry!r} has no site")
            if (action in WIRE_ACTIONS) != (site == WIRE_SITE):
                raise ValueError(
                    f"fault entry {entry!r}: wire actions {WIRE_ACTIONS} "
                    f"pair only with site {WIRE_SITE!r} and vice versa"
                )
            fields: dict = {"action": action, "site": site}
            for kv in filter(None, (p.strip() for p in kvs.split(","))):
                k, _, v = kv.partition("=")
                if k not in ("rank", "step", "batch", "world", "req", "ms",
                             "sticky", "exit_code"):
                    raise ValueError(f"unknown fault field {k!r} in {entry!r}")
                fields[k] = int(v)
            specs.append(FaultSpec(**fields))
        return cls(specs, marker_dir=marker_dir)

    @classmethod
    def from_env(cls, environ=os.environ) -> "FaultPlan | None":
        # Direct read by design: must stay stdlib-importable pre-platform
        # (see _log); utils.env pulls the jax-importing utils package.
        # Names ARE registered; only the accessor differs.
        # mlspark-lint: ok env-direct-read -- pre-platform module, see above
        text = environ.get(ENV_PLAN)
        if not text:
            return None
        plan = cls.from_spec(
            text,
            marker_dir=environ.get(ENV_MARKER_DIR),  # mlspark-lint: ok env-direct-read -- pre-platform module, see from_env
        )
        if plan.marker_dir is None and any(
            s.action in ("crash", "stall") for s in plan.specs
        ):
            _log().warning(
                "%s has crash/stall faults but no %s marker dir: they will "
                "re-fire on every process restart (gang retries cannot "
                "succeed)", ENV_PLAN, ENV_MARKER_DIR,
            )
        return plan

    # -- one-shot bookkeeping ------------------------------------------------
    def _already_fired(self, spec: FaultSpec) -> bool:
        if spec.key in self._fired:
            return True
        return bool(
            self.marker_dir
            and os.path.exists(os.path.join(self.marker_dir, spec.key))
        )

    def _mark_fired(self, spec: FaultSpec) -> None:
        self._fired.add(spec.key)
        if self.marker_dir:
            # Marker lands BEFORE the action: an os._exit fault must not be
            # able to re-arm on the retried attempt. Atomic rename so a kill
            # mid-write can't leave a half-marker.
            os.makedirs(self.marker_dir, exist_ok=True)
            tmp = os.path.join(self.marker_dir, f".{spec.key}.tmp.{os.getpid()}")
            with open(tmp, "w") as f:
                f.write(str(time.time()))
            os.replace(tmp, os.path.join(self.marker_dir, spec.key))

    def pending(self, site: str, *, rank: int | None = None,
                step: int | None = None, batch: int | None = None,
                world: int | None = None,
                req: int | None = None) -> FaultSpec | None:
        """The first matching not-yet-fired spec, or None. Marks it fired.

        ``sticky`` specs are exempt from one-shot consumption: they match
        on every call, but the marker is still written once so a drill
        can prove the fault actually engaged."""
        with self._lock:
            for spec in self.specs:
                if not spec.matches(site, rank, step, batch, world, req):
                    continue
                fired = self._already_fired(spec)
                if fired and not spec.sticky:
                    continue
                if not fired:
                    self._mark_fired(spec)
                return spec
        return None


# -- process-global plan ------------------------------------------------------
_PLAN: FaultPlan | None = None
_PLAN_LOADED = False
_HEARTBEATS_SUSPENDED = threading.Event()


def install(plan: FaultPlan | None) -> None:
    """Install (or, with None, clear) the process-global plan — the test
    hook; spawned workers get theirs from the environment instead."""
    global _PLAN, _PLAN_LOADED
    _PLAN = plan
    _PLAN_LOADED = True
    if plan is None:
        _HEARTBEATS_SUSPENDED.clear()


def clear() -> None:
    install(None)
    global _PLAN_LOADED
    _PLAN_LOADED = False  # next maybe_fault re-reads the environment


def active_plan() -> FaultPlan | None:
    """The installed plan, lazily falling back to ``MLSPARK_FAULTS``."""
    global _PLAN, _PLAN_LOADED
    if not _PLAN_LOADED:
        _PLAN = FaultPlan.from_env()
        _PLAN_LOADED = True
    return _PLAN


def heartbeats_suspended() -> bool:
    """True once a ``stall`` fault fired — the runner's heartbeat thread
    polls this so a stalled worker goes silent exactly like a hung one."""
    return _HEARTBEATS_SUSPENDED.is_set()


def _env_rank() -> int | None:
    # mlspark-lint: ok env-direct-read -- pre-platform module, see from_env
    v = os.environ.get("MLSPARK_PROCESS_ID")
    return int(v) if v is not None else None


def _env_world() -> int | None:
    # mlspark-lint: ok env-direct-read -- pre-platform module, see from_env
    v = os.environ.get("MLSPARK_NUM_PROCESSES")
    return int(v) if v is not None else None


def maybe_fault(site: str, *, step: int | None = None,
                batch: int | None = None, rank: int | None = None,
                world: int | None = None) -> None:
    """Instrumentation point: fire the first pending fault matching this
    site/coordinate, else return immediately. ``rank`` defaults to this
    process's ``MLSPARK_PROCESS_ID``, ``world`` to
    ``MLSPARK_NUM_PROCESSES`` (how elastic drills pin a fault to one
    world size along the shrink path)."""
    if site == WIRE_SITE:
        raise ValueError(
            "wire faults are queried via wire_fault(), not executed by "
            "maybe_fault() — the HTTP handler owns the behavior"
        )
    plan = active_plan()
    if plan is None:
        return
    spec = plan.pending(
        site, rank=_env_rank() if rank is None else rank, step=step,
        batch=batch, world=_env_world() if world is None else world,
    )
    if spec is None:
        return
    _log().warning("fault injection firing: %s (site=%s step=%s batch=%s)",
                spec.key, site, step, batch)
    # Flight recorder BEFORE the action: an os._exit'd (or stalled) process
    # gets no later chance, so the dump must happen while we still run. The
    # failing step's span_start is already in the event log (instrumented
    # call sites open their span before maybe_fault). Lazy import + broad
    # swallow: this module must stay stdlib-importable and a recorder
    # problem must never mask the drill itself.
    try:
        from machine_learning_apache_spark_tpu.telemetry import recorder

        recorder.dump_flight(
            f"fault:{spec.key}",
            extra={"site": site, "step": step, "batch": batch,
                   "action": spec.action},
        )
    except Exception:
        pass
    if spec.action == "raise":
        raise FaultInjected(f"injected fault {spec.key}")
    if spec.action == "crash":
        # os._exit: no atexit, no finally, no result file — the closest
        # in-process stand-in for SIGKILL/OOM/preemption.
        os._exit(spec.exit_code)
    if spec.action == "stall":
        # Go silent: heartbeats stop (the monitor's missed-heartbeat path
        # must notice), and this thread hangs until the gang teardown's
        # SIGTERM/SIGKILL reaps the process.
        _HEARTBEATS_SUSPENDED.set()
        while True:
            time.sleep(3600)


def wire_fault(*, rank: int | None = None,
               req: int | None = None) -> FaultSpec | None:
    """Query the plan for a wire fault matching this HTTP exchange.

    Unlike :func:`maybe_fault` this *returns* the matched spec instead of
    executing it — wire behaviors (delay / black-hole / torn / corrupt /
    drip) are implemented by the caller (``ReplicaServer``'s handler),
    which owns the socket. ``rank`` defaults to ``MLSPARK_PROCESS_ID``;
    ``req`` is the caller's per-server exchange ordinal. One-shot (or
    sticky) bookkeeping is consumed exactly as for process faults."""
    plan = active_plan()
    if plan is None:
        return None
    spec = plan.pending(
        WIRE_SITE, rank=_env_rank() if rank is None else rank, req=req,
    )
    if spec is None or spec.action not in WIRE_ACTIONS:
        # A crash/raise/stall spec can never parse with site "wire", so a
        # non-wire action here means a hand-built plan; refuse quietly.
        return None
    if spec.key not in getattr(wire_fault, "_logged", set()):
        wire_fault._logged = getattr(wire_fault, "_logged", set()) | {spec.key}
        _log().warning("wire fault engaging: %s (rank=%s req=%s)",
                       spec.key, rank, req)
    return spec


__all__ = [
    "ENV_MARKER_DIR",
    "ENV_PLAN",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "WIRE_ACTIONS",
    "WIRE_SITE",
    "active_plan",
    "clear",
    "heartbeats_suspended",
    "install",
    "maybe_fault",
    "wire_fault",
]
