"""Version-skew shims for the narrow band of JAX API the repo spans.

The hosting images pin different jax releases (0.4.37 today; newer
elsewhere), and two surfaces we depend on moved between them:

- ``shard_map`` graduated from ``jax.experimental.shard_map.shard_map``
  to ``jax.shard_map``. Same signature for the keyword form we use
  (``mesh=/in_specs=/out_specs=``).
- The pallas-TPU compiler-params dataclass was renamed
  ``TPUCompilerParams`` -> ``CompilerParams`` inside
  ``jax.experimental.pallas.tpu``; the fields we pass
  (``dimension_semantics``) are unchanged.

Every caller imports the symbol from here instead of version-guessing
inline, so the next rename is a one-file fix.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
    # The graduated shard_map tracks replication through transposition:
    # differentiating a replicated (in_spec P()) input automatically
    # psums its cotangents across the mesh, so grads come back as the
    # true global reduction with no explicit collective.
    implicit_replicated_grad_reduce = True
else:  # jax <= 0.4.x: pre-graduation home
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    # 0.4.x forces a trade: its replication checker can't statically
    # infer that transpose-inserted psums leave grads replicated (it
    # rejects data_parallel's P() grads out_spec outright), and turning
    # the checker off ALSO turns off the replication-aware transpose
    # rewrite — cotangents of replicated inputs are NOT psummed. So the
    # shim disables the checker, and callers that differentiate through
    # shard_map must consult the flag below and reduce grads themselves.
    implicit_replicated_grad_reduce = False

    def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):  # type: ignore[no-redef]
        kwargs.setdefault("check_rep", False)
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )


def pcast_varying(x, axes):
    """``jax.lax.pcast(x, axes, to="varying")`` where it exists.

    On pre-vma jax (<= 0.4.x) there is no varying/replicated type system
    to satisfy — the fallback ``shard_map`` above runs ``check_rep=False``
    — so the cast is semantically the identity.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axes), to="varying")
    return x


def set_num_cpu_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices, under either mechanism.

    ``jax_num_cpu_devices`` is the config option on current jax; 0.4.x
    predates it, where the only lever is the
    ``--xla_force_host_platform_device_count`` XLA flag. Both act only
    BEFORE backend initialization — same contract as the caller had with
    the plain config update.
    """
    import os

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}".strip()
            )


def pallas_tpu_compiler_params(**kwargs):
    """Build the pallas-TPU ``compiler_params`` object under either name.

    Imported lazily: pallas drags in the Mosaic lowering stack, which not
    every process touching this module needs (e.g. the launcher).
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


__all__ = [
    "shard_map",
    "implicit_replicated_grad_reduce",
    "pcast_varying",
    "pallas_tpu_compiler_params",
    "set_num_cpu_devices",
]
