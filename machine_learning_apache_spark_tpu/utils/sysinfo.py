"""Host-level machine context for benchmark artifacts.

Every BENCH_*.json gate in this repo compares throughput numbers across
rounds, and the single biggest source of phantom regressions is the
machine itself: a bench run while a sibling job hogs the cores produces
a knee 20% low and a gate failure nothing in the code caused. The fix
is not to refuse to run — CI machines are shared by design — but to
**stamp the evidence**: every bench artifact carries the load average
observed at preflight and a ``contended`` verdict, so a regression
reviewer's first check ("was the machine busy?") is answered by the
artifact instead of by archaeology.

stdlib-only; ``os.getloadavg`` is POSIX-only and absence degrades to
``None`` fields rather than a crash (the verdict is then ``False`` —
unknown is not evidence of contention).
"""

from __future__ import annotations

import os

#: 1-minute load above this fraction of the core count reads as "another
#: workload is competing for CPU". 0.5 is deliberately sensitive: a bench
#: should saturate its own cores from a quiet start, so even half-busy
#: at preflight means the numbers are suspect.
CONTENTION_LOAD_FRACTION = 0.5


def host_load() -> dict:
    """One preflight snapshot: load averages, core count, and the
    ``contended`` verdict (1-minute load > ``CONTENTION_LOAD_FRACTION``
    × cores). JSON-ready — benches embed it verbatim."""
    cores = os.cpu_count()
    try:
        load_1m, load_5m, load_15m = os.getloadavg()
    except (OSError, AttributeError):
        load_1m = load_5m = load_15m = None
    contended = bool(
        load_1m is not None
        and cores
        and load_1m > CONTENTION_LOAD_FRACTION * cores
    )
    return {
        "load_1m": None if load_1m is None else round(load_1m, 2),
        "load_5m": None if load_5m is None else round(load_5m, 2),
        "load_15m": None if load_15m is None else round(load_15m, 2),
        "cores": cores,
        "contended": contended,
    }


__all__ = ["CONTENTION_LOAD_FRACTION", "host_load"]
