from machine_learning_apache_spark_tpu.utils.prng import KeySeq, key
from machine_learning_apache_spark_tpu.utils.logging import get_logger, rank_zero_print
from machine_learning_apache_spark_tpu.utils.timing import Timer, timed_span
from machine_learning_apache_spark_tpu.utils.profiling import (
    StepWindowTracer,
    annotate,
    device_trace,
    step_annotation,
)

__all__ = [
    "KeySeq",
    "key",
    "get_logger",
    "rank_zero_print",
    "Timer",
    "timed_span",
    "StepWindowTracer",
    "annotate",
    "device_trace",
    "step_annotation",
]
