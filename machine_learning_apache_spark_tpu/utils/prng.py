"""PRNG key plumbing.

The reference relies on torch's global RNG (implicit seeding); JAX is
functional, so every source of randomness threads an explicit key. ``KeySeq``
is the framework's single convention for that.
"""

from __future__ import annotations

import jax


def key(seed: int) -> jax.Array:
    return jax.random.key(seed)


class KeySeq:
    """A splitting key sequence: ``ks = KeySeq(1234); k1 = ks(); k2 = ks()``.

    Deterministic given the seed; also supports named folds so distributed
    hosts can derive per-rank streams: ``ks.fold(process_index)``.
    """

    def __init__(self, seed_or_key: int | jax.Array) -> None:
        self._key = key(seed_or_key) if isinstance(seed_or_key, int) else seed_or_key

    def __call__(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def split(self, n: int) -> jax.Array:
        self._key, *subs = jax.random.split(self._key, n + 1)
        return jax.numpy.stack(subs)

    def fold(self, data: int) -> "KeySeq":
        return KeySeq(jax.random.fold_in(self._key, data))
