"""Central ``MLSPARK_*`` environment contract — the env registry.

Every environment variable the framework reads is declared here once,
with its type, default, subsystem, and a one-line description. Runtime
code resolves values through the typed accessors (``get_str`` /
``get_int`` / ``get_float`` / ``get_bool``) instead of raw ``os.environ``
reads, which buys three things:

- **One contract.** ``docs/ENV.md`` is generated from this registry
  (``tools/mlspark_lint.py --write-env-docs``) and the ``env`` lint pass
  fails the build when docs and code drift, when an unregistered
  ``MLSPARK_*`` name appears anywhere in the package, or when a module
  bypasses the registry with a direct ``os.environ`` read.
- **Typed, validated reads.** A malformed value raises one uniform
  ``ValueError`` naming the variable and its expected type, instead of a
  bare ``int()`` traceback deep inside a worker.
- **Greppable writes.** The launcher's worker-env plumbing goes through
  :func:`put_into`, so setting an unregistered name is an error at the
  driver, not a silently ignored variable in every rank.

The registry declarations are **pure literals** on purpose: the lint
suite (``analysis/envcheck.py``) extracts them by AST without importing
the package, so the contract is checkable without paying a JAX import.

Stdlib-only module body; importable anywhere in the package. Note that
importing it still triggers the package ``__init__`` — modules that must
stay cheap *before* the heavy framework import (``launcher/runner.py``'s
pre-import section) keep direct reads with a lint pragma instead.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Mapping, MutableMapping

__all__ = [
    "EnvVar",
    "REGISTRY",
    "register",
    "lookup",
    "registered_names",
    "is_set",
    "raw",
    "get_str",
    "get_int",
    "get_float",
    "get_bool",
    "put_into",
]

_UNSET = object()

#: Values ``get_bool`` reads as False; anything else set is True. Matches
#: the historical ``MLSPARK_TELEMETRY=0`` semantics in telemetry.events.
FALSY = ("0", "false", "off", "no", "")


@dataclass(frozen=True)
class EnvVar:
    """One declared environment variable: the contract row."""

    name: str
    type: str  # "str" | "int" | "float" | "bool" | "path" | "spec"
    default: Any
    subsystem: str
    description: str
    choices: tuple[str, ...] | None = None


REGISTRY: dict[str, EnvVar] = {}


def register(
    name: str,
    *,
    type: str,
    default: Any,
    subsystem: str,
    description: str,
    choices: tuple[str, ...] | None = None,
) -> EnvVar:
    """Declare one variable. Names must be unique and ``MLSPARK_``-prefixed."""
    if not name.startswith("MLSPARK_"):
        raise ValueError(f"env contract covers MLSPARK_* names only, got {name!r}")
    if name in REGISTRY:
        raise ValueError(f"duplicate env registration: {name}")
    if type not in ("str", "int", "float", "bool", "path", "spec"):
        raise ValueError(f"{name}: unknown type {type!r}")
    var = EnvVar(name, type, default, subsystem, description, choices)
    REGISTRY[name] = var
    return var


# -- the contract ------------------------------------------------------------
# Keep every field a literal: analysis/envcheck.py extracts these calls by
# AST (no package import) to generate docs/ENV.md and to know the set of
# legal names. Grouped by subsystem; docs render in this order.

# core / platform bootstrap
register(
    "MLSPARK_PLATFORM", type="str", default=None, subsystem="core",
    description="JAX platform override applied through the config API at "
    "first package import (reliable where the JAX_PLATFORMS env var is "
    "not, e.g. images whose sitecustomize pre-registers a TPU plugin). "
    "Example: `cpu`, `tpu`.",
)
register(
    "MLSPARK_CPU_DEVICES", type="int", default=None, subsystem="core",
    description="Number of virtual CPU devices to request before backend "
    "init (local mesh bring-up; the fake-cluster lever).",
)
register(
    "MLSPARK_NO_NATIVE_TEXT", type="bool", default=False, subsystem="data",
    description="Force the pure-Python tokenizer/vocab paths even when the "
    "native extension is importable (bit-identical fallback; used by "
    "parity tests).",
)

# session / train config (ConfigBase.from_env reads MLSPARK_<FIELD> for
# every dataclass field; these are the fields that exist today)
register(
    "MLSPARK_APP_NAME", type="str", default="mlspark-tpu", subsystem="session",
    description="Session app name (`spark.app.name` analogue; set by "
    "`mlspark-submit --name`).",
)
register(
    "MLSPARK_EXECUTOR_INSTANCES", type="int", default=0, subsystem="session",
    description="Requested world size (`spark.executor.instances` "
    "analogue). 0 derives from the JAX runtime.",
)
register(
    "MLSPARK_EXECUTOR_CORES", type="int", default=1, subsystem="session",
    description="Per-executor core request (SessionConfig field; "
    "accounting only on TPU).",
)
register(
    "MLSPARK_EXECUTOR_MEMORY", type="str", default="1g", subsystem="session",
    description="Per-executor memory request (SessionConfig field; "
    "accounting only on TPU).",
)
register(
    "MLSPARK_DRIVER_MEMORY", type="str", default="1g", subsystem="session",
    description="Driver memory request (SessionConfig field; accounting "
    "only on TPU).",
)
register(
    "MLSPARK_COORDINATOR_ADDRESS", type="str", default="", subsystem="session",
    description="SessionConfig rendezvous override (`host:port`); the "
    "launcher's MLSPARK_COORDINATOR is the usual channel.",
)
register(
    "MLSPARK_COMPILATION_CACHE_DIR", type="path", default="", subsystem="session",
    description="Persistent XLA compilation-cache directory (compiles "
    "reused across processes; 20-60s/program on remote controllers).",
)
register(
    "MLSPARK_BATCH_SIZE", type="int", default=32, subsystem="train",
    description="TrainConfig.batch_size override (recipe hyperparameter).",
)
register(
    "MLSPARK_EPOCHS", type="int", default=3, subsystem="train",
    description="TrainConfig.epochs override (recipe hyperparameter).",
)
register(
    "MLSPARK_LEARNING_RATE", type="float", default=1e-3, subsystem="train",
    description="TrainConfig.learning_rate override (recipe hyperparameter).",
)
register(
    "MLSPARK_OPTIMIZER", type="str", default="adam", subsystem="train",
    description="TrainConfig.optimizer override.", choices=("adam", "sgd"),
)
register(
    "MLSPARK_SEED", type="int", default=1234, subsystem="train",
    description="TrainConfig.seed override (PRNG seed for the recipes).",
)
register(
    "MLSPARK_LOG_EVERY", type="int", default=100, subsystem="train",
    description="TrainConfig.log_every override (per-N-batch print cadence).",
)
register(
    "MLSPARK_DTYPE", type="str", default="float32", subsystem="train",
    description="TrainConfig.dtype override (compute dtype; `bfloat16` "
    "for MXU-friendly runs).",
)

# launcher / rendezvous / gang liveness
register(
    "MLSPARK_COORDINATOR", type="str", default=None, subsystem="launcher",
    description="Rendezvous coordinator `host:port` the launcher writes "
    "into every worker (maps onto jax.distributed.initialize; "
    "MASTER_ADDR/MASTER_PORT are the torch-style aliases).",
)
register(
    "MLSPARK_NUM_PROCESSES", type="int", default=1, subsystem="launcher",
    description="Gang world size as this worker sees it (WORLD_SIZE "
    "analogue; shrinks under elastic resume).",
)
register(
    "MLSPARK_PROCESS_ID", type="int", default=0, subsystem="launcher",
    description="This worker's gang rank (RANK analogue); also the rank "
    "label telemetry and fault plans key on.",
)
register(
    "MLSPARK_GANG_ATTEMPT", type="int", default=0, subsystem="launcher",
    description="Which all-or-nothing gang restart attempt this worker "
    "belongs to (0 on the first launch).",
)
register(
    "MLSPARK_HEARTBEAT_FILE", type="path", default=None, subsystem="launcher",
    description="Per-rank heartbeat file the worker rewrites every "
    "interval; the GangMonitor's liveness signal (mtime) and "
    "gang-status payload (JSON content).",
)
register(
    "MLSPARK_HEARTBEAT_INTERVAL", type="float", default=1.0, subsystem="launcher",
    description="Seconds between heartbeat rewrites.",
)
register(
    "MLSPARK_ELASTIC", type="bool", default=False, subsystem="launcher",
    description="Set by Distributor(elastic=True): workers' fit() "
    "reshards old-topology checkpoints onto a shrunken mesh instead of "
    "refusing them (train/reshard.py).",
)

# parallel / comms
register(
    "MLSPARK_DP_MODE", type="str", default="replicated", subsystem="parallel",
    description="Data-parallel update mode for fit() when dp_mode= is not "
    "passed.", choices=("replicated", "zero1"),
)
register(
    "MLSPARK_ZERO1_BUCKET_BYTES", type="int", default=4194304, subsystem="parallel",
    description="ZeRO-1 bucket size in bytes (the comm/compute overlap "
    "pipeline grain).",
)
register(
    "MLSPARK_ZERO1_OVERLAP", type="bool", default=True, subsystem="parallel",
    description="Per-bucket update/allgather overlap schedule on (default) "
    "or off (serial reference path; bit-identical either way).",
)
register(
    "MLSPARK_COMMS_DTYPE", type="str", default="float32", subsystem="parallel",
    description="ZeRO-1 wire dtype for reduce-scatter/allgather "
    "(sub-fp32 shrinks bytes; int8 uses EQuARX-style per-bucket scales).",
    choices=("float32", "bfloat16", "int8"),
)

# serving
register(
    "MLSPARK_SERVE_KV_MODE", type="str", default="paged", subsystem="serving",
    description="KV-cache discipline for ServingEngine when kv_mode= is "
    "not passed: `paged` (ragged paged attention, the default) or "
    "`padded` (per-bucket rectangle oracle / beam path).",
    choices=("padded", "paged"),
)
register(
    "MLSPARK_SERVE_KV_DTYPE", type="str", default="float32", subsystem="serving",
    description="Paged KV store dtype: `float32`, or `int8` with "
    "per-page scales (paged+greedy only; padded/beam engines reject it).",
    choices=("float32", "int8"),
)

# telemetry / observability plane
register(
    "MLSPARK_TELEMETRY", type="bool", default=True, subsystem="telemetry",
    description="Master switch; `0` makes every telemetry entry point a "
    "no-op singleton (zero cost, zero threads).",
)
register(
    "MLSPARK_TELEMETRY_DIR", type="path", default=None, subsystem="telemetry",
    description="Where rank JSONL exports, flight dumps, and port "
    "sidecars land; unset means no file exports.",
)
register(
    "MLSPARK_TELEMETRY_HTTP", type="int", default=None, subsystem="telemetry",
    description="Port for the per-process observability HTTP server "
    "(/metrics, /healthz, /statusz, /flightz); 0 = ephemeral; unset = no "
    "server, zero threads.",
)
register(
    "MLSPARK_TELEMETRY_EVENTS", type="int", default=4096, subsystem="telemetry",
    description="Flight-recorder event-ring capacity (events kept for "
    "/flightz and crash dumps).",
)
register(
    "MLSPARK_TRACE", type="bool", default=True, subsystem="telemetry",
    description="Distributed tracing switch: mint/propagate trace "
    "contexts across router -> replica -> engine hops (no-op whenever "
    "MLSPARK_TELEMETRY=0).",
)
register(
    "MLSPARK_TRACE_SAMPLE", type="float", default=1.0, subsystem="telemetry",
    description="Head-based trace sampling probability in [0, 1]; the "
    "decision is made once per request at the router/engine entry point "
    "and inherited by every hop.",
)

# ingest
register(
    "MLSPARK_INGEST_BUFFER", type="int", default=2, subsystem="ingest",
    description="Host-side prefetch depth in batches (0 = synchronous "
    "batch assembly).",
)
register(
    "MLSPARK_INGEST_DEVICE_PREFETCH", type="int", default=2, subsystem="ingest",
    description="Batches kept resident on-device ahead of consumption "
    "(double buffering at 2; 0 disables the device stage).",
)
register(
    "MLSPARK_INGEST_TAIL", type="str", default="pad", subsystem="ingest",
    description="Epoch-tail policy: `pad` (collective-safe wrap-pad) or "
    "`drop`.", choices=("pad", "drop"),
)
register(
    "MLSPARK_INGEST_CHUNK_LINES", type="int", default=1024, subsystem="ingest",
    description="Lines per parser call in the streaming file readers "
    "(native-parser batching grain).",
)

# fleet / multi-replica serving
register(
    "MLSPARK_FLEET_DIR", type="path", default=None, subsystem="fleet",
    description="Where fleet sidecars (`fleet_rank<k>.json`) and the "
    "`fleet_stop` marker live; defaults to the telemetry dir.",
)
register(
    "MLSPARK_FLEET_PORT", type="int", default=0, subsystem="fleet",
    description="Replica data-plane port (0 = ephemeral, the only sane "
    "choice for a local gang).",
)
register(
    "MLSPARK_FLEET_POLICY", type="str", default="affinity", subsystem="fleet",
    description="Router dispatch policy when policy= is not passed.",
    choices=("round_robin", "least_loaded", "affinity"),
)
register(
    "MLSPARK_FLEET_SCRAPE_INTERVAL", type="float", default=0.5, subsystem="fleet",
    description="Router scrape-loop period in seconds (replica /statusz "
    "polling).",
)
register(
    "MLSPARK_FLEET_TENANT_MAX_IN_FLIGHT", type="int", default=None, subsystem="fleet",
    description="Per-tenant in-flight admission quota (unset = no tenant "
    "quota).",
)
register(
    "MLSPARK_FLEET_INTERACTIVE_DEADLINE_S", type="float", default=10.0, subsystem="fleet",
    description="Default deadline for the `interactive` SLO tier.",
)
register(
    "MLSPARK_FLEET_INTERACTIVE_MAX_IN_FLIGHT", type="int", default=64, subsystem="fleet",
    description="In-flight cap for the `interactive` SLO tier.",
)
register(
    "MLSPARK_FLEET_BATCH_DEADLINE_S", type="float", default=120.0, subsystem="fleet",
    description="Default deadline for the `batch` SLO tier.",
)
register(
    "MLSPARK_FLEET_BATCH_MAX_IN_FLIGHT", type="int", default=256, subsystem="fleet",
    description="In-flight cap for the `batch` SLO tier.",
)
register(
    "MLSPARK_FLEET_HEDGE", type="bool", default=False, subsystem="fleet",
    description="Enable straggler hedging: after the hedge delay, the "
    "router issues a duplicate dispatch to a second healthy replica; "
    "first response wins, the loser is cancelled via /v1/cancel.",
)
register(
    "MLSPARK_FLEET_HEDGE_TIERS", type="str", default="interactive", subsystem="fleet",
    description="Comma-separated SLO tiers eligible for hedging "
    "(latency-sensitive tiers only by default; batch work rides the "
    "plain retry taxonomy).",
)
register(
    "MLSPARK_FLEET_HEDGE_DELAY_FACTOR", type="float", default=3.0, subsystem="fleet",
    description="Hedge delay as a multiple of the admission layer's "
    "observed service-time EWMA — a dispatch outstanding this much "
    "longer than typical is presumed straggling.",
)
register(
    "MLSPARK_FLEET_HEDGE_MIN_DELAY_S", type="float", default=0.05, subsystem="fleet",
    description="Floor on the hedge delay, so a cold or noisy EWMA "
    "cannot make every request fan out twice.",
)

# fleet autoscaling (closed loop: SLO burn / queue depth -> replica count)
register(
    "MLSPARK_AUTOSCALE_MIN_REPLICAS", type="int", default=1, subsystem="autoscale",
    description="Floor on the autoscaler's replica target; scale-down "
    "never drains below this.",
)
register(
    "MLSPARK_AUTOSCALE_MAX_REPLICAS", type="int", default=8, subsystem="autoscale",
    description="Ceiling on the autoscaler's replica target; scale-up "
    "never spawns past this.",
)
register(
    "MLSPARK_AUTOSCALE_BURN_UP", type="float", default=0.1, subsystem="autoscale",
    description="Scale up when any tier's SLO burn EWMA (scraped replica "
    "rollup or router-side gauge) is at/above this miss fraction.",
)
register(
    "MLSPARK_AUTOSCALE_BURN_DOWN", type="float", default=0.01, subsystem="autoscale",
    description="Burn EWMA must be at/below this before the load signal "
    "may vote to scale down (both signals must be cold).",
)
register(
    "MLSPARK_AUTOSCALE_QUEUE_UP", type="float", default=4.0, subsystem="autoscale",
    description="Scale up when mean in-flight per healthy replica is "
    "at/above this depth.",
)
register(
    "MLSPARK_AUTOSCALE_QUEUE_DOWN", type="float", default=1.0, subsystem="autoscale",
    description="Mean in-flight per healthy replica must be at/below "
    "this before a scale-down vote counts.",
)
register(
    "MLSPARK_AUTOSCALE_HYSTERESIS_TICKS", type="int", default=2, subsystem="autoscale",
    description="Consecutive scrape ticks a signal must hold before the "
    "autoscaler acts on it (one bad scrape cannot thrash the fleet).",
)
register(
    "MLSPARK_AUTOSCALE_COOLDOWN_S", type="float", default=5.0, subsystem="autoscale",
    description="Minimum seconds between autoscale actions (either "
    "direction); the anti-thrash backstop behind hysteresis.",
)
register(
    "MLSPARK_AUTOSCALE_DRAIN_DEADLINE_S", type="float", default=30.0, subsystem="autoscale",
    description="Seconds a draining replica gets to retire its in-flight "
    "work before it is torn down anyway.",
)
register(
    "MLSPARK_AUTOSCALE_DRAIN_BATCH_SHED", type="float", default=0.5, subsystem="autoscale",
    description="While a drain is in progress the batch tier's admission "
    "budget is multiplied by this factor (interactive is untouched) so "
    "shed capacity comes out of batch work first.",
)

# fault injection
register(
    "MLSPARK_FAULTS", type="spec", default=None, subsystem="faults",
    description="Fault-injection plan (semicolon-separated grammar, see "
    "utils/faults.py): which site fails, on which rank/world/occurrence, "
    "and how.",
)
register(
    "MLSPARK_FAULTS_DIR", type="path", default=None, subsystem="faults",
    description="Where fault-marker files are written (evidence that an "
    "injected fault fired, robust to the process dying mid-action).",
)

# examples / demo scripts (read only by examples/, registered so the
# contract and docs cover them)
register(
    "MLSPARK_SMOKE", type="bool", default=False, subsystem="examples",
    description="Shrink example model/data for a quick CPU check "
    "(examples/advanced_translator.py).",
)
register(
    "MLSPARK_WORKDIR", type="path", default=None, subsystem="examples",
    description="Example scripts' scratch directory (default: a fresh "
    "tempdir).",
)
register(
    "MLSPARK_DIST_PLATFORM", type="str", default="cpu", subsystem="examples",
    description="Platform the distributed example scripts pass to "
    "Distributor(platform=...); empty = let each worker pick.",
)


# -- typed accessors ----------------------------------------------------------
def lookup(name: str) -> EnvVar:
    """The declaration for ``name``; raises ``KeyError`` with the fix for
    unregistered names (the runtime mirror of the lint rule)."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not in the MLSPARK_* env contract; declare it in "
            "machine_learning_apache_spark_tpu/utils/env.py (and regenerate "
            "docs/ENV.md with tools/mlspark_lint.py --write-env-docs)"
        ) from None


def registered_names() -> frozenset[str]:
    return frozenset(REGISTRY)


def raw(name: str, environ: Mapping[str, str] | None = None) -> str | None:
    """The unparsed value, or None when unset. Registry-checked."""
    lookup(name)
    env = os.environ if environ is None else environ
    return env.get(name)


def is_set(name: str, environ: Mapping[str, str] | None = None) -> bool:
    return raw(name, environ) is not None


def _resolve_default(var: EnvVar, default: Any) -> Any:
    return var.default if default is _UNSET else default


def get_str(
    name: str, default: Any = _UNSET, environ: Mapping[str, str] | None = None
) -> str | None:
    var = lookup(name)
    v = raw(name, environ)
    if v is None:
        return _resolve_default(var, default)
    if var.choices is not None and v not in var.choices:
        raise ValueError(
            f"{name} must be one of {list(var.choices)}, got {v!r}"
        )
    return v


def get_int(
    name: str, default: Any = _UNSET, environ: Mapping[str, str] | None = None
) -> int | None:
    var = lookup(name)
    v = raw(name, environ)
    if v is None:
        return _resolve_default(var, default)
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}") from None


def get_float(
    name: str, default: Any = _UNSET, environ: Mapping[str, str] | None = None
) -> float | None:
    var = lookup(name)
    v = raw(name, environ)
    if v is None:
        return _resolve_default(var, default)
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a float, got {v!r}") from None


def get_bool(
    name: str, default: Any = _UNSET, environ: Mapping[str, str] | None = None
) -> bool:
    """Truthy unless the value is one of :data:`FALSY` (case-insensitive);
    unset resolves the default."""
    var = lookup(name)
    v = raw(name, environ)
    if v is None:
        return bool(_resolve_default(var, default))
    return v.strip().lower() not in FALSY


def put_into(
    env: MutableMapping[str, str], name: str, value: Any
) -> MutableMapping[str, str]:
    """Write one contract variable into a (worker) env mapping — the
    launcher-side half of the contract. Registry-checked so a typo'd name
    fails at the driver, not as a silently ignored variable in the gang."""
    var = lookup(name)
    s = str(value)
    if var.choices is not None and s not in var.choices:
        raise ValueError(
            f"{name} must be one of {list(var.choices)}, got {value!r}"
        )
    env[name] = s
    return env
