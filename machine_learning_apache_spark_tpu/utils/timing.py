"""Wall-clock timing spans.

The reference self-times every training run with ``time.time()`` pairs (19
sites; e.g. ``pytorch_multilayer_perceptron.py:98,118-120``) plus a rolling
per-100-batch span (``pytorch_machine_translator.py:150,199-205``). This
module is the one structured implementation of that vocabulary.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class Timer:
    """Start/stop wall-clock timer with rolling-span support."""

    name: str = "train"
    _start: float = field(default_factory=time.perf_counter, repr=False)
    elapsed: float = 0.0

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self._start
        return self.elapsed

    def lap(self) -> float:
        """Elapsed since last start/lap; restarts the span (the reference's
        rolling 100-batch timer, ``pytorch_machine_translator.py:199-205``)."""
        now = time.perf_counter()
        span = now - self._start
        self._start = now
        return span


@contextlib.contextmanager
def timed_span(label: str, emit=None):
    """``with timed_span("Training Time"):`` — prints ``<label>: <sec>`` on
    exit, the reference's universal metric line (SURVEY.md §6)."""
    t = Timer(label).start()
    try:
        yield t
    finally:
        t.stop()
        (emit or print)(f"{label}: {t.elapsed:.3f} sec")
