"""Wall-clock timing spans — re-homed onto :mod:`..telemetry.spans`.

The reference self-times every training run with ``time.time()`` pairs (19
sites; e.g. ``pytorch_multilayer_perceptron.py:98,118-120``) plus a rolling
per-100-batch span (``pytorch_machine_translator.py:150,199-205``).
``Timer`` and ``timed_span`` are the structured implementation of that
vocabulary; they now live in the telemetry subsystem so ad-hoc timings and
structured trace spans share one event log. This module remains as the
back-compat import surface — existing call sites keep working unchanged.
"""

from __future__ import annotations

from machine_learning_apache_spark_tpu.telemetry.spans import (  # noqa: F401
    Timer,
    timed_span,
)

__all__ = ["Timer", "timed_span"]
