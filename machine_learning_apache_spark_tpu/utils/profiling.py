"""Profiling / tracing hooks — the observability the reference lacks.

The reference's only instrumentation is manual ``time.time()`` pairs
(SURVEY.md §5 tracing: 19 sites, plus one unused ``timeit`` import at
``pytorch_cnn.py:6``). The framework keeps that span vocabulary
(``utils.timing``) and adds the real thing: ``jax.profiler`` device traces
viewable in TensorBoard/XProf (compiled-step timelines, HBM usage, ICI
collectives), plus named trace annotations that label host-side regions
inside the trace.

Usage:
    with device_trace("/tmp/trace"):          # whole-region trace
        run_steps()

    fit(..., profile_dir="/tmp/trace")        # trace a step window mid-run

    with annotate("tokenize"):                # label host work in the trace
        pipe(texts)
"""

from __future__ import annotations

import contextlib

import jax

from machine_learning_apache_spark_tpu.telemetry import spans as _spans
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _sync_local_devices() -> None:
    """Fence: a trivial computation per local device executes only after all
    previously-dispatched work on that device — required before stop_trace
    or the traced steps' device timeline is still in flight and missing."""
    import jax.numpy as jnp

    probes = [
        jax.device_put(jnp.zeros(()), d) + 0 for d in jax.local_devices()
    ]
    jax.block_until_ready(probes)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a jax.profiler trace for the enclosed region into
    ``log_dir`` (TensorBoard: ``tensorboard --logdir <log_dir>``)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        _sync_local_devices()
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", log_dir)


class _AnnotatedRegion:
    """Context manager pairing a jax.profiler.TraceAnnotation (device
    timeline) with a telemetry span (host event log): one entry point, the
    region shows up in both worlds. The telemetry half is the shared no-op
    when disabled, so the hot serving decode path pays only the
    TraceAnnotation it already paid."""

    __slots__ = ("_trace", "_span")

    def __init__(self, name: str, **kwargs):
        self._trace = jax.profiler.TraceAnnotation(name, **kwargs)
        self._span = _spans.span(name, **kwargs)

    def __enter__(self):
        self._span.__enter__()
        self._trace.__enter__()
        return self

    def __exit__(self, *exc):
        self._trace.__exit__(*exc)
        self._span.__exit__(*exc)


def annotate(name: str, **kwargs):
    """Named region annotation appearing on the trace timeline (and, when
    telemetry is enabled, as a span on the event log)."""
    return _AnnotatedRegion(name, **kwargs)


def step_annotation(step: int):
    """Marks one training step; XProf groups per-step statistics by these."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


class StepWindowTracer:
    """Trace a ``[start, stop)`` window of steps inside a long run — the
    usual profiling pattern: skip compile/warmup steps, capture a few steady
    -state ones, stop before the trace gets huge.
    """

    def __init__(self, log_dir: str | None, *, start: int = 2, stop: int = 5):
        if stop <= start:
            raise ValueError(f"empty trace window [{start}, {stop})")
        self.log_dir = log_dir
        self.start, self.stop = start, stop
        self._active = False
        self._done = False

    def on_step(self, step: int) -> None:
        # Boundary-crossing (>=), not equality: callers may advance the step
        # counter in strides > 1 (fit's steps_per_call dispatches K steps
        # per on_step call) and must still enter/leave the window. Order
        # matters: the stop check applies only while active, so a single
        # stride crossing BOTH boundaries still starts a trace (covering at
        # least its own dispatch; the next call closes it).
        if self.log_dir is None:
            return
        if self._active and step >= self.stop:
            self.close()
            return
        if not self._active and not self._done and step >= self.start:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def close(self) -> None:
        if self._active:
            _sync_local_devices()
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            log.info(
                "profiler trace (steps %d-%d) written to %s",
                self.start, self.stop, self.log_dir,
            )
