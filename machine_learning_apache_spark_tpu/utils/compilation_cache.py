"""Persistent XLA compilation cache — compile once, reuse across processes.

The reference pays no compile cost (eager PyTorch); the XLA trade is
whole-program optimization up front. That cost recurs per *process*
(in-memory jit caches die with it) unless the persistent cache is enabled:
with a cache dir set, every qualifying XLA compilation is written to disk
keyed by program+backend fingerprint and later processes deserialize
instead of recompiling. On remote-controller topologies, where a compile is
an expensive RPC (20-60 s observed per program on the tunneled dev chip),
this converts every repeat run — reruns of an example, a resumed training
job, the bench's fresh process — into a cache hit.

Scope: caching is keyed by backend fingerprint, so a dir can be shared
between CPU and TPU runs without cross-contamination; entries below the
min-compile-time floor are skipped (tiny programs recompile faster than
they deserialize).
"""

from __future__ import annotations

import os

import jax

from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

_DEFAULT_MIN_COMPILE_SECS = 1.0


def enable_compilation_cache(
    cache_dir: str,
    *,
    min_compile_time_secs: float = _DEFAULT_MIN_COMPILE_SECS,
) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; creates the directory. Returns the resolved path. Safe to
    call before or after backend initialization (the cache config keys are
    not backend-locked, unlike ``jax_platforms``).
    """
    path = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    # Cache every entry size: the floor that matters is compile *time*
    # (set above); a large program that compiled slowly but serializes
    # small is exactly the case worth keeping.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _reset_cache_singleton()
    log.info("persistent compilation cache at %s", path)
    return path


def _reset_cache_singleton() -> None:
    """Drop JAX's lazily-initialized cache object so a dir change takes
    effect: once the internal singleton binds to a directory, later
    ``jax_compilation_cache_dir`` updates are silently ignored for the
    life of the process. Private API, so best-effort — on JAX versions
    without it, only the FIRST enable in a process picks the dir."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def jit_cache_size(fn) -> int | None:
    """Number of compiled programs held by one ``jax.jit`` callable —
    the in-process compile counter behind the serving engine's
    zero-recompiles-after-warmup invariant (each new (shape, dtype)
    signature adds one). Reads jit's private cache-size probe; returns
    None on jax builds that don't expose it (the counter is diagnostics,
    never a dependency)."""
    try:
        return int(fn._cache_size())
    except AttributeError:
        return None


def disable_compilation_cache() -> None:
    """Undo ``enable_compilation_cache`` (all three config keys — the cache
    settings are process-global JAX config, so a session that doesn't want
    an earlier session's cache must reset explicitly)."""
    jax.config.update("jax_compilation_cache_dir", None)  # JAX defaults
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", _DEFAULT_MIN_COMPILE_SECS
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _reset_cache_singleton()
