"""Rank-aware logging.

The reference's observability is bare ``print()`` (SURVEY.md §5). Here the
same metric vocabulary is emitted through one module, gated to rank 0 by
default so multi-host runs don't interleave N copies of every line.
"""

from __future__ import annotations

import logging
import sys

import jax

_LOGGERS: dict[str, logging.Logger] = {}
# stdout by default: the examples' metric lines (Training Time, accuracy)
# reproduce the reference's print vocabulary on the reference's stream.
_DEFAULT_STREAM = sys.stdout


def get_logger(name: str = "mlspark") -> logging.Logger:
    if name not in _LOGGERS:
        logger = logging.getLogger(name)
        if not logger.handlers:
            handler = logging.StreamHandler(_DEFAULT_STREAM)
            handler.setFormatter(
                logging.Formatter("[%(asctime)s %(name)s] %(message)s", "%H:%M:%S")
            )
            logger.addHandler(handler)
            logger.setLevel(logging.INFO)
            logger.propagate = False
        _LOGGERS[name] = logger
    return _LOGGERS[name]


class _StderrProxy:
    """File-like object resolving ``sys.stderr`` at EVERY write.

    Binding the stderr *object* at reroute time breaks under test
    harnesses that swap/close ``sys.stderr`` per test (pytest capture): a
    later log line would hit a closed stream and spray '--- Logging
    error ---'. Late binding always reaches whatever stderr currently is.
    """

    def write(self, s):  # noqa: D102 — file protocol
        return sys.stderr.write(s)

    def flush(self):  # noqa: D102
        return sys.stderr.flush()


def route_logging_to_stderr() -> None:
    """Retarget every package logger (existing and future) to stderr.

    For processes whose stdout is a machine-parsed artifact — bench.py's
    contract is ONE JSON line on stdout — where a stray log line (e.g. the
    compilation-cache enable notice) would corrupt the artifact stream.
    """
    global _DEFAULT_STREAM
    proxy = _StderrProxy()
    _DEFAULT_STREAM = proxy
    for logger in _LOGGERS.values():
        for h in logger.handlers:
            # FileHandler subclasses StreamHandler; retargeting one would
            # silently divert a file log to stderr.
            if isinstance(h, logging.StreamHandler) and not isinstance(
                h, logging.FileHandler
            ):
                h.setStream(proxy)


def rank_zero_print(*args, all_ranks: bool = False, **kwargs) -> None:
    """``print`` that only fires on process 0 (the reference prints from every
    rank — e.g. the training prints inside ``train_func`` at
    ``distributed_cnn.py:188-191`` run once per executor)."""
    if all_ranks or jax.process_index() == 0:
        print(*args, **kwargs)
