"""Flight recorder — dump the last ~512 events at the moment of failure.

PR 2's ``GangFailure`` says *which* rank died; the flight recorder says
*what it was doing*: the tail of the event log (recent spans, counters,
annotations) written to ``flight_<rank>.json`` the instant something
goes wrong. Dump sites:

- ``utils.faults.maybe_fault`` — BEFORE executing a crash/stall action
  (an ``os._exit`` process cannot dump afterwards);
- ``train.loop.fit`` — unhandled exception out of the training loop;
- ``serving.engine._quarantine`` — a poisoned batch;
- ``launcher.runner`` — worker exception / SIGTERM from gang teardown;
- ``launcher.monitor.GangMonitor`` — driver-side, on gang failure
  (``flight_driver.json``).

Dumps go to ``MLSPARK_TELEMETRY_DIR`` (the Distributor points it at the
gang workdir, next to the heartbeat files, unless the caller set it);
with no directory configured the dump is skipped. ``dump_flight`` must
never raise — it runs on paths that are already failing.
"""

from __future__ import annotations

import json
import os
import time

from machine_learning_apache_spark_tpu.telemetry import events as _events

#: How many trailing events a flight dump carries.
FLIGHT_CAPACITY = 512


def _flight_name() -> str:
    rank = _events._env_rank()
    return f"flight_{'driver' if rank is None else rank}.json"


def flight_path(directory: str | None = None) -> str | None:
    """Where this process's flight dump would land, or None if nowhere."""
    d = directory or _events.telemetry_dir()
    return os.path.join(d, _flight_name()) if d else None


def dump_flight(
    reason: str,
    directory: str | None = None,
    extra: dict | None = None,
    capacity: int = FLIGHT_CAPACITY,
) -> str | None:
    """Write the event-log tail to ``flight_<rank>.json``; returns the path
    (None if disabled / no directory). Swallows all errors — this runs on
    failure paths and must not mask the original exception."""
    try:
        if not _events.enabled():
            return None
        path = flight_path(directory)
        if path is None:
            return None
        log = _events.get_log()
        events = [ev.to_dict() for ev in log.tail(capacity)]
        payload = {
            "artifact": "flight",
            "reason": reason,
            "rank": _events._env_rank(),
            "pid": os.getpid(),
            "wall": round(time.time(), 6),
            "dropped": log.dropped,
            "event_count": len(events),
            "events": events,
        }
        if extra:
            payload["extra"] = extra
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def load_flight(path: str) -> dict:
    """Read a flight dump back (report tooling / tests)."""
    with open(path) as f:
        return json.load(f)


__all__ = ["FLIGHT_CAPACITY", "dump_flight", "flight_path", "load_flight"]
