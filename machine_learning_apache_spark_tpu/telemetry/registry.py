"""Process-global metrics registry: counters, gauges, raw-sample histograms.

Where ``spans`` answers "what was this process doing and for how long",
the registry answers "how much / how many right now": requests admitted,
tokens emitted, queue depth, step time distribution. Metrics live in
named scopes (``serving``, ``train``, ``launcher``) so two subsystems can
both own a ``requests`` counter without colliding.

Histograms keep raw samples (bounded) and compute percentiles with the
same nearest-rank ``percentile`` the serving ledger uses — one
definition of p99 across the whole repo. The import is lazy: serving's
metrics module is jax-free but lives under the heavy package root, and
the registry must stay importable in stdlib-only contexts.

Exports: ``snapshot()`` (plain dicts, JSON-ready) and
``to_prometheus_text()`` (text exposition format, one scrape surface for
the whole process).

Disabled mode (``MLSPARK_TELEMETRY=0``) hands out module-level no-op
metric singletons — counter bumps in hot loops cost one cached-boolean
check and a method call, no allocation, no lock.
"""

from __future__ import annotations

import threading

from machine_learning_apache_spark_tpu.telemetry import events as _events

_DEFAULT_HIST_SAMPLES = 4096


def _fallback_percentile(samples, p):
    if not samples:
        return None
    xs = sorted(samples)
    k = max(0, min(len(xs) - 1, int(round(p / 100.0 * len(xs) + 0.5)) - 1))
    return xs[k]


_PERCENTILE_FN = None


def _percentile(samples, p):
    """Nearest-rank percentile — the serving ledger's definition, reused.
    Falls back to a local copy if serving isn't importable (it is in every
    supported environment; the fallback keeps stdlib-only contexts safe).
    The import resolves once, at first use, and the function is cached —
    histogram summaries call this per quantile, and an import-machinery
    round-trip per call is measurable under scrape load."""
    global _PERCENTILE_FN
    if _PERCENTILE_FN is None:
        try:
            from machine_learning_apache_spark_tpu.serving.metrics import (
                percentile,
            )

            _PERCENTILE_FN = percentile
        except Exception:
            _PERCENTILE_FN = _fallback_percentile
    return _PERCENTILE_FN(samples, p)


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "scope", "_lock", "_value")

    def __init__(self, scope: str, name: str):
        self.scope = scope
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time level; goes up and down."""

    __slots__ = ("name", "scope", "_lock", "_value")

    def __init__(self, scope: str, name: str):
        self.scope = scope
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class HistogramMetric:
    """Raw-sample histogram (bounded ring of recent samples) with
    nearest-rank percentiles. Count/sum are cumulative over all observed
    samples even after the ring evicts old ones."""

    __slots__ = ("name", "scope", "_lock", "_samples", "_max", "count", "sum")

    def __init__(
        self, scope: str, name: str, max_samples: int = _DEFAULT_HIST_SAMPLES
    ):
        self.scope = scope
        self.name = name
        self._lock = threading.Lock()
        self._samples: list[float] = []
        self._max = max_samples
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            if len(self._samples) >= self._max:
                # Overwrite in ring order; cheaper than pop(0) on a list.
                self._samples[(self.count - 1) % self._max] = value
            else:
                self._samples.append(value)

    def percentile(self, p: float):
        with self._lock:
            samples = list(self._samples)
        return _percentile(samples, p)

    def summary(self) -> dict:
        with self._lock:
            samples = list(self._samples)
            count, total = self.count, self.sum
        return {
            "count": count,
            "sum": round(total, 6),
            "p50": _percentile(samples, 50),
            "p90": _percentile(samples, 90),
            "p99": _percentile(samples, 99),
            "max": max(samples) if samples else None,
        }


class _NoopMetric:
    """Stands in for Counter/Gauge/Histogram when telemetry is off."""

    __slots__ = ()
    name = scope = ""
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, amount: float = 1.0) -> None:  # noqa: ARG002
        pass

    def dec(self, amount: float = 1.0) -> None:  # noqa: ARG002
        pass

    def set(self, value: float) -> None:  # noqa: ARG002
        pass

    def observe(self, value: float) -> None:  # noqa: ARG002
        pass

    def percentile(self, p: float):  # noqa: ARG002
        return None

    def summary(self) -> dict:
        return {"count": 0, "sum": 0.0, "p50": None, "p90": None,
                "p99": None, "max": None}


NOOP_METRIC = _NoopMetric()


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


class MetricsRegistry:
    """Named scopes of metrics, one registry per process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple[str, str], object] = {}

    def _get(self, cls, scope: str, name: str, **kw):
        key = (scope, name)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(scope, name, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {scope}.{name} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
        return m

    def counter(self, scope: str, name: str) -> Counter:
        return self._get(Counter, scope, name)

    def gauge(self, scope: str, name: str) -> Gauge:
        return self._get(Gauge, scope, name)

    def histogram(
        self, scope: str, name: str,
        max_samples: int = _DEFAULT_HIST_SAMPLES,
    ) -> HistogramMetric:
        return self._get(
            HistogramMetric, scope, name, max_samples=max_samples
        )

    def snapshot(self) -> dict:
        """Plain-dict view, scope → name → value/summary. JSON-ready."""
        with self._lock:
            metrics = dict(self._metrics)
        out: dict[str, dict] = {}
        for (scope, name), m in sorted(metrics.items()):
            bucket = out.setdefault(scope, {})
            if isinstance(m, HistogramMetric):
                bucket[name] = m.summary()
            else:
                bucket[name] = m.value
        return out

    def to_prometheus_text(self) -> str:
        """Text exposition format. Counters/gauges one sample each;
        histograms as summary-style quantile lines plus _count/_sum.
        Each sample carries a ``rank`` label when running inside a gang."""
        rank = _events._env_rank()
        labels = f'{{rank="{rank}"}}' if rank is not None else ""
        with self._lock:
            metrics = dict(self._metrics)
        lines: list[str] = []
        for (scope, name), m in sorted(metrics.items()):
            full = _sanitize(f"mlspark_{scope}_{name}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {full} counter")
                lines.append(f"{full}{labels} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full}{labels} {m.value:g}")
            elif isinstance(m, HistogramMetric):
                s = m.summary()
                lines.append(f"# TYPE {full} summary")
                for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    v = s[key]
                    if v is None:
                        continue
                    if labels:
                        qlabels = labels[:-1] + f',quantile="{q}"}}'
                    else:
                        qlabels = f'{{quantile="{q}"}}'
                    lines.append(f"{full}{qlabels} {v:g}")
                lines.append(f"{full}_count{labels} {s['count']}")
                lines.append(f"{full}_sum{labels} {s['sum']:g}")
        return "\n".join(lines) + ("\n" if lines else "")


class _NoopRegistry:
    """Disabled-mode registry: every lookup returns the shared no-op metric."""

    def counter(self, scope: str, name: str):  # noqa: ARG002
        return NOOP_METRIC

    def gauge(self, scope: str, name: str):  # noqa: ARG002
        return NOOP_METRIC

    def histogram(self, scope: str, name: str, max_samples: int = 0):  # noqa: ARG002
        return NOOP_METRIC

    def snapshot(self) -> dict:
        return {}

    def to_prometheus_text(self) -> str:
        return ""


NOOP_REGISTRY = _NoopRegistry()

_REGISTRY: MetricsRegistry | None = None
_REGISTRY_LOCK = threading.Lock()


def get_registry():
    """The process-global registry (no-op singleton when disabled)."""
    global _REGISTRY
    if not _events.enabled():
        return NOOP_REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def reset() -> None:
    """Drop the global registry — test hook."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = None


__all__ = [
    "Counter",
    "Gauge",
    "HistogramMetric",
    "MetricsRegistry",
    "NOOP_METRIC",
    "NOOP_REGISTRY",
    "get_registry",
    "reset",
]
