"""Gang-wide telemetry aggregation: merge per-rank JSONL into one report.

Each rank exports its event log as ``telemetry_rank<k>.jsonl`` (the
launcher's runner does this in its exit path, next to the heartbeat
files). This module merges those files into:

- a **per-phase table** — for every span name, per-rank and overall
  count / mean / p50 / p99 durations;
- a **skew report** — for every phase seen on >1 rank, which rank is
  slowest (by mean duration), the slowest/fastest ratio, and the spread.
  In an SPMD gang every rank runs the same program, so a phase whose
  mean differs across ranks is a straggler signature — this is the
  slowest-rank attribution the comms-optimization PRs need.

Percentiles are nearest-rank via the same ``percentile`` definition the
registry and serving ledger use. Consumed by rank 0 in-process or by
``tools/telemetry_report.py`` offline; pure functions over plain dicts,
stdlib-only.
"""

from __future__ import annotations

import glob
import json
import os
import re

from machine_learning_apache_spark_tpu.telemetry.registry import _percentile

RANK_FILE_RE = re.compile(r"telemetry_rank(\d+)\.jsonl$")


def rank_file_name(rank: int) -> str:
    return f"telemetry_rank{rank}.jsonl"


def write_rank_file(directory: str, rank: int | None = None) -> str:
    """Export this process's event log as ``telemetry_rank<k>.jsonl`` in
    ``directory``; returns the path. Rank defaults to the env rank (0 when
    running outside a gang)."""
    from machine_learning_apache_spark_tpu.telemetry import events as _events

    if rank is None:
        r = _events._env_rank()
        rank = 0 if r is None else r
    path = os.path.join(directory, rank_file_name(rank))
    _events.get_log().export_jsonl(path)
    return path


# Parse cache for rank exports, keyed on (mtime_ns, size). Live status
# tooling (gang_status --watch, the bench's periodic merges) re-merges
# the same directory on an interval, and most rank files are unchanged
# between ticks — exports are written once by atomic os.replace, so an
# (mtime_ns, size) match means byte-identical content. Entries hold the
# parsed event dicts; every consumer that mutates an event copies it
# first (merge_rank_files stamps rank onto a dict() copy), so sharing
# the parsed lists is safe.
_PARSE_CACHE: dict[str, tuple[tuple[int, int], list[dict]]] = {}
_PARSE_CACHE_MAX = 64


def clear_parse_cache() -> None:
    """Drop the JSONL parse cache (test hook)."""
    _PARSE_CACHE.clear()


def load_jsonl(path: str) -> list[dict]:
    """Read one rank's JSONL export (cached by mtime+size — see
    ``_PARSE_CACHE``). Tolerates a trailing partial line (a killed
    writer) but raises on malformed interior lines."""
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        stamp = None
    if stamp is not None:
        hit = _PARSE_CACHE.get(path)
        if hit is not None and hit[0] == stamp:
            # Fresh outer list per hit — a caller appending to its result
            # must not grow the cached copy.
            return list(hit[1])
    out: list[dict] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn final line from a killed process
            raise
    if stamp is not None:
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            # Bounded: evict the oldest insertion (a watch loop touches
            # the same few files; anything beyond the bound is churn).
            _PARSE_CACHE.pop(next(iter(_PARSE_CACHE)))
        # The cache keeps its own outer list: the miss path hands the
        # caller the same isolation a hit does.
        _PARSE_CACHE[path] = (stamp, list(out))
    return out


def find_rank_files(directory: str) -> dict[int, str]:
    """``{rank: path}`` for every ``telemetry_rank<k>.jsonl`` in a dir."""
    out: dict[int, str] = {}
    for path in glob.glob(os.path.join(directory, "telemetry_rank*.jsonl")):
        m = RANK_FILE_RE.search(os.path.basename(path))
        if m:
            out[int(m.group(1))] = path
    return dict(sorted(out.items()))


def merge_rank_files(paths: dict[int, str]) -> list[dict]:
    """Concatenate rank exports into one event list, stamping each event's
    ``rank`` with the rank from the FILE NAME (authoritative — an event
    recorded before the env contract was set carries rank=None)."""
    merged: list[dict] = []
    for rank, path in sorted(paths.items()):
        for ev in load_jsonl(path):
            ev = dict(ev)
            ev["rank"] = rank
            merged.append(ev)
    return merged


def _stats(durations: list[float]) -> dict:
    return {
        "count": len(durations),
        "mean": round(sum(durations) / len(durations), 6),
        "p50": _percentile(durations, 50),
        "p99": _percentile(durations, 99),
        "max": max(durations),
    }


def phase_table(events: list[dict]) -> dict:
    """Per-span-name duration stats: ``{phase: {"overall": stats,
    "ranks": {rank: stats}}}``, built from ``span_end`` events."""
    by_phase: dict[str, dict[int | None, list[float]]] = {}
    for ev in events:
        if ev.get("kind") != "span_end" or ev.get("value") is None:
            continue
        by_phase.setdefault(ev["name"], {}).setdefault(
            ev.get("rank"), []
        ).append(float(ev["value"]))
    table: dict[str, dict] = {}
    for phase in sorted(by_phase):
        per_rank = by_phase[phase]
        all_durs = [d for durs in per_rank.values() for d in durs]
        table[phase] = {
            "overall": _stats(all_durs),
            "ranks": {
                rank: _stats(durs)
                for rank, durs in sorted(
                    per_rank.items(), key=lambda kv: (kv[0] is None, kv[0])
                )
            },
        }
    return table


def skew_report(table: dict) -> dict:
    """Straggler attribution from a ``phase_table``: for every phase with
    >1 rank, the slowest rank by mean duration and the slow/fast ratio."""
    report: dict[str, dict] = {}
    for phase, entry in table.items():
        ranks = {
            r: s for r, s in entry["ranks"].items() if r is not None
        }
        if len(ranks) < 2:
            continue
        slowest = max(ranks, key=lambda r: ranks[r]["mean"])
        fastest = min(ranks, key=lambda r: ranks[r]["mean"])
        fast_mean = ranks[fastest]["mean"]
        slow_mean = ranks[slowest]["mean"]
        report[phase] = {
            "slowest_rank": slowest,
            "fastest_rank": fastest,
            "slowest_mean": slow_mean,
            "fastest_mean": fast_mean,
            "skew_ratio": round(slow_mean / fast_mean, 4)
            if fast_mean > 0 else None,
            "spread": round(slow_mean - fast_mean, 6),
        }
    return report


#: Exposed-comms fraction above which a run is called comms-bound: more
#: than this share of (exposed-collective + step) time spent in collectives
#: the schedule could not hide behind compute.
COMMS_BOUND_THRESHOLD = 0.25


def comms_report(events: list[dict], table: dict | None = None) -> dict:
    """Comms rollup for the gang report: per-rank totals of the ``comms.*``
    counter events (wire bytes the zero1 step moved, with bytes/step where
    the emitter recorded a step count in ``attrs``) plus the duration
    stats of any ``comms.*`` span phases (the collective p50/p99 the
    comms-bench emits). Empty dicts when the run had no comms activity —
    the renderer then omits the section's tables.

    The ``overlap`` block splits the same wire bytes into overlapped vs
    exposed (the ``comms.bytes_overlapped`` / ``comms.bytes_exposed``
    counters the zero1 step emits — the static pipeline model, overlap on
    hides ``(nb-1)/nb`` of each collective behind compute). ``verdict``
    mirrors the ingest input-bound verdict: exposed-collective time —
    measured ``comms.*`` span time scaled by the exposed byte fraction —
    as a share of exposed + ``train.step`` time, comms-bound above
    ``COMMS_BOUND_THRESHOLD``. ``None`` when the run recorded no
    ``comms.*`` spans (a fused training step cannot time its in-program
    collectives; only the bench's standalone collectives produce spans).
    """
    table = phase_table(events) if table is None else table
    counters: dict[str, dict] = {}
    for ev in events:
        name = str(ev.get("name", ""))
        if ev.get("kind") != "counter" or not name.startswith("comms."):
            continue
        per_rank = counters.setdefault(name, {})
        entry = per_rank.setdefault(
            ev.get("rank"), {"total": 0.0, "steps": 0}
        )
        entry["total"] += float(ev.get("value") or 0.0)
        entry["steps"] += int((ev.get("attrs") or {}).get("steps") or 0)
    for per_rank in counters.values():
        for entry in per_rank.values():
            entry["per_step"] = (
                round(entry["total"] / entry["steps"], 1)
                if entry["steps"] else None
            )
    collectives = {
        phase: entry
        for phase, entry in table.items()
        if phase.startswith("comms.")
    }

    def _counter_total(name: str) -> float:
        return sum(
            entry["total"] for entry in counters.get(name, {}).values()
        )

    overlap: dict = {}
    exposed_b = _counter_total("comms.bytes_exposed")
    overlapped_b = _counter_total("comms.bytes_overlapped")
    if exposed_b or overlapped_b:
        wire = exposed_b + overlapped_b
        overlap = {
            "bytes_exposed": int(exposed_b),
            "bytes_overlapped": int(overlapped_b),
            "overlapped_fraction": round(overlapped_b / wire, 4) if wire else None,
        }

    def _phase_total(phase: str) -> float:
        entry = table.get(phase)
        if not entry:
            return 0.0
        return entry["overall"]["mean"] * entry["overall"]["count"]

    comms_time = sum(_phase_total(phase) for phase in collectives)
    exposed_fraction_of_bytes = (
        exposed_b / (exposed_b + overlapped_b)
        if (exposed_b + overlapped_b) > 0 else 1.0
    )
    exposed_time = comms_time * exposed_fraction_of_bytes
    step_time = _phase_total("train.step") + _phase_total("train.step_group")
    comms_fraction = (
        round(exposed_time / (exposed_time + step_time), 4)
        if (exposed_time + step_time) > 0 and comms_time > 0 else None
    )
    verdict = None
    if comms_fraction is not None and step_time > 0:
        verdict = (
            "comms-bound"
            if comms_fraction > COMMS_BOUND_THRESHOLD
            else "compute-bound"
        )
    return {
        "counters": {
            name: dict(sorted(
                per_rank.items(), key=lambda kv: (kv[0] is None, kv[0])
            ))
            for name, per_rank in sorted(counters.items())
        },
        "collectives": collectives,
        "overlap": overlap,
        "comms_fraction": comms_fraction,
        "verdict": verdict,
    }


#: Stall fraction above which a run is called input-bound: more than this
#: share of (step + data-wait) time spent waiting on the input pipeline.
INPUT_BOUND_THRESHOLD = 0.1


def ingest_report(events: list[dict], table: dict | None = None) -> dict:
    """Input-pipeline rollup for the gang report, from the ``data.*``
    event family the ingest subsystem emits:

    - ``phases``: the ``data.*`` rows of the phase table (read/pack/h2d
      stage durations plus ``data.wait``, the consumer's time blocked on
      the host prefetch buffer);
    - ``buffer_occupancy``: per-rank stats over the
      ``data.buffer_occupancy`` gauge (sampled at every producer put —
      a buffer pinned at 0 means the producer can't keep up, pinned at
      capacity means the device is the bottleneck);
    - ``counters``: per-rank totals of the ``data.*`` counter events
      (records/batches per epoch, H2D bytes);
    - ``stall_fraction`` / ``verdict``: the input-bound vs compute-bound
      classification — stall time (``data.wait``, or ``data.read`` for an
      unbuffered pipeline, which then blocks the step loop directly) as a
      fraction of stall + ``train.step`` time, input-bound above
      ``INPUT_BOUND_THRESHOLD``.

    Empty sub-dicts when the run had no ingest activity — the renderer
    then omits the section.
    """
    table = phase_table(events) if table is None else table
    occupancy: dict[int | None, list[float]] = {}
    counters: dict[str, dict] = {}
    for ev in events:
        name = str(ev.get("name", ""))
        if not name.startswith("data."):
            continue
        if ev.get("kind") == "gauge" and name == "data.buffer_occupancy":
            occupancy.setdefault(ev.get("rank"), []).append(
                float(ev.get("value") or 0.0)
            )
        elif ev.get("kind") == "counter":
            per_rank = counters.setdefault(name, {})
            entry = per_rank.setdefault(ev.get("rank"), {"total": 0.0})
            entry["total"] += float(ev.get("value") or 0.0)
    phases = {
        phase: entry
        for phase, entry in table.items()
        if phase.startswith("data.")
    }

    def _total(phase: str) -> float:
        entry = table.get(phase)
        if not entry:
            return 0.0
        return entry["overall"]["mean"] * entry["overall"]["count"]

    stall = _total("data.wait") or _total("data.read")
    step = _total("train.step") + _total("train.step_group")
    stall_fraction = (
        round(stall / (stall + step), 4) if (stall + step) > 0 else None
    )
    verdict = None
    if stall_fraction is not None and step > 0:
        verdict = (
            "input-bound"
            if stall_fraction > INPUT_BOUND_THRESHOLD
            else "compute-bound"
        )
    return {
        "phases": phases,
        "buffer_occupancy": {
            rank: _stats(vals)
            for rank, vals in sorted(
                occupancy.items(), key=lambda kv: (kv[0] is None, kv[0])
            )
        },
        "counters": {
            name: dict(sorted(
                per_rank.items(), key=lambda kv: (kv[0] is None, kv[0])
            ))
            for name, per_rank in sorted(counters.items())
        },
        "stall_fraction": stall_fraction,
        "verdict": verdict,
    }


def serving_report(events: list[dict], table: dict | None = None) -> dict:
    """Serving rollup for the gang report, from the ``serving.*`` event
    family the engine emits:

    - ``phases``: the ``serving.*`` rows of the phase table (submit and
      batch/launch span durations);
    - ``batches_by_mode``: span counts and mean duration split by the
      ``mode`` attr ("padded" vs "paged") — a mixed-mode gang shows both;
    - ``counters``: per-rank totals of ``serving.*`` counter events
      (today: ``tokens_real``/``tokens_padded``, the padding-waste pair
      ``ServingMetrics.on_token_slots`` mirrors into the event stream);
    - ``padding_waste``: computed-slot waste across every rank, the
      fraction of slots the compiled programs spent on padding;
    - ``quarantines`` / ``rejects`` / ``expired``: containment and
      admission annotations, summed.

    Empty sub-dicts when the run served nothing — the renderer then
    omits the section.
    """
    table = phase_table(events) if table is None else table
    counters: dict[str, dict] = {}
    by_mode: dict[str, dict] = {}
    quarantines = rejects = expired = 0
    for ev in events:
        name = str(ev.get("name", ""))
        if not name.startswith("serving."):
            continue
        kind = ev.get("kind")
        attrs = ev.get("attrs") or {}
        if kind == "counter":
            per_rank = counters.setdefault(name, {})
            entry = per_rank.setdefault(ev.get("rank"), {"total": 0.0})
            entry["total"] += float(ev.get("value") or 0.0)
        elif kind == "span_end" and name == "serving.batch":
            mode = str(attrs.get("mode") or "padded")
            entry = by_mode.setdefault(mode, {"count": 0, "total_s": 0.0})
            entry["count"] += 1
            entry["total_s"] += float(ev.get("value") or 0.0)
        elif kind == "annotation":
            if name == "serving.quarantine":
                quarantines += 1
            elif name == "serving.queue.reject":
                rejects += 1
            elif name == "serving.queue.expire":
                expired += int(attrs.get("count") or 0)
    for entry in by_mode.values():
        entry["mean_s"] = (
            round(entry["total_s"] / entry["count"], 6)
            if entry["count"] else None
        )
        entry["total_s"] = round(entry["total_s"], 6)

    def _sum(name: str) -> float:
        return sum(
            e["total"] for e in counters.get(name, {}).values()
        )

    real, padded = _sum("serving.tokens_real"), _sum("serving.tokens_padded")
    return {
        "phases": {
            phase: entry
            for phase, entry in table.items()
            if phase.startswith("serving.")
        },
        "batches_by_mode": dict(sorted(by_mode.items())),
        "counters": {
            name: dict(sorted(
                per_rank.items(), key=lambda kv: (kv[0] is None, kv[0])
            ))
            for name, per_rank in sorted(counters.items())
        },
        "padding_waste": round(1.0 - real / padded, 4) if padded else None,
        "quarantines": quarantines,
        "rejects": rejects,
        "expired": expired,
    }


#: How many slowest requests the gang-level request report lists.
REQUEST_REPORT_SLOWEST = 8


def request_report(events: list[dict]) -> dict:
    """Per-request latency breakdown across the gang, from the
    ``serving.request`` annotations ``ServingMetrics.on_trace`` emits
    (one per retired request, attrs = the trace's breakdown dict):

    - ``breakdown``: stats over each latency component — queue_wait
      (submit → admit), ttft (submit → first token), service (admit →
      retire), total (submit → retire);
    - ``by_prefill``: request counts split by prefill kind ("hit" for
      prefix-cache attach, "miss"/"padded" for computed prefill);
    - ``slowest``: the ``REQUEST_REPORT_SLOWEST`` worst requests by total
      latency, with rank and trace id — the exemplars to chase.

    Empty dicts when no requests retired — the renderer omits the section.
    """
    fields = ("queue_wait_s", "ttft_s", "service_s", "total_s")
    samples: dict[str, list[float]] = {f: [] for f in fields}
    by_prefill: dict[str, int] = {}
    rows: list[dict] = []
    for ev in events:
        if ev.get("kind") != "annotation" or ev.get("name") != "serving.request":
            continue
        attrs = ev.get("attrs") or {}
        for f in fields:
            v = attrs.get(f)
            if v is not None:
                samples[f].append(float(v))
        kind = attrs.get("prefill")
        if kind is not None:
            by_prefill[str(kind)] = by_prefill.get(str(kind), 0) + 1
        rows.append({
            "rank": ev.get("rank"),
            "trace_id": attrs.get("trace_id"),
            "total_s": attrs.get("total_s"),
            "queue_wait_s": attrs.get("queue_wait_s"),
            "ttft_s": attrs.get("ttft_s"),
            "launches": attrs.get("launches"),
            "prefill": kind,
        })
    rows.sort(key=lambda r: r.get("total_s") or 0.0, reverse=True)
    return {
        "breakdown": {
            f: _stats(vals) for f, vals in samples.items() if vals
        },
        "by_prefill": dict(sorted(by_prefill.items())),
        "slowest": rows[:REQUEST_REPORT_SLOWEST],
    }


def fleet_report(events: list[dict]) -> dict:
    """Router-side rollup from the ``fleet.request`` annotations
    ``FleetRouter.submit`` emits (one per routed request, attrs =
    outcome / replica / tier / tenant / retries / total_s / status):

    - ``by_outcome`` / ``by_tier`` / ``by_tenant``: request counts —
      the admission and drain story in numbers;
    - ``per_replica``: how many requests each replica actually served,
      with end-to-end latency stats — the routing-skew evidence;
    - ``retries``: total re-dispatches (refused/backpressured replicas
      the router routed around);
    - ``latency``: end-to-end (admission → response) stats across all
      completed requests.

    Empty dict when no ``fleet.request`` annotations exist — the
    renderer then omits the section.
    """
    outcomes: dict[str, int] = {}
    tiers: dict[str, int] = {}
    tenants: dict[str, int] = {}
    per_replica: dict[int, dict] = {}
    totals: list[float] = []
    retries = 0
    n = 0
    for ev in events:
        if ev.get("kind") != "annotation" or ev.get("name") != "fleet.request":
            continue
        attrs = ev.get("attrs") or {}
        n += 1
        outcome = str(attrs.get("outcome"))
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        tier = attrs.get("tier")
        if tier is not None:
            tiers[str(tier)] = tiers.get(str(tier), 0) + 1
        tenant = attrs.get("tenant")
        if tenant is not None:
            tenants[str(tenant)] = tenants.get(str(tenant), 0) + 1
        retries += int(attrs.get("retries") or 0)
        total_s = attrs.get("total_s")
        if total_s is not None:
            totals.append(float(total_s))
        replica = attrs.get("replica")
        if replica is not None:
            entry = per_replica.setdefault(
                int(replica), {"requests": 0, "_totals": []}
            )
            entry["requests"] += 1
            if total_s is not None:
                entry["_totals"].append(float(total_s))
    if not n:
        return {}
    return {
        "requests": n,
        "by_outcome": dict(sorted(outcomes.items())),
        "by_tier": dict(sorted(tiers.items())),
        "by_tenant": dict(sorted(tenants.items())),
        "retries": retries,
        "latency": _stats(totals) if totals else None,
        "per_replica": {
            rank: {
                "requests": entry["requests"],
                "latency": _stats(entry["_totals"])
                if entry["_totals"] else None,
            }
            for rank, entry in sorted(per_replica.items())
        },
    }


def replica_skew(rows: list[dict]) -> dict:
    """Fleet-level load-skew verdict from scrape-plane status rows (the
    ``ScrapeLoop.rows()`` / ``tools/gang_status.py`` shape): which
    replica ran hottest/coldest by tokens/sec and how lopsided the split
    was. ``hottest_share`` is the hottest replica's fraction of fleet
    throughput — 1/N is a perfectly balanced fleet. Empty dict below two
    replicas with throughput numbers (skew needs a comparison)."""
    usable = [
        r for r in rows
        if isinstance(r.get("tokens_per_sec"), (int, float))
    ]
    if len(usable) < 2:
        return {}
    hottest = max(usable, key=lambda r: r["tokens_per_sec"])
    coldest = min(usable, key=lambda r: r["tokens_per_sec"])
    fleet_tps = sum(r["tokens_per_sec"] for r in usable)
    cold_tps = coldest["tokens_per_sec"]
    return {
        "replicas": {
            r["rank"]: {
                "tokens_per_sec": r.get("tokens_per_sec"),
                "in_flight": r.get("in_flight"),
                "queue_depth": r.get("queue_depth"),
                "occupancy": r.get("occupancy"),
                "prefix_hit_rate": r.get("prefix_hit_rate"),
            }
            for r in sorted(usable, key=lambda r: r["rank"])
        },
        "hottest_rank": hottest["rank"],
        "coldest_rank": coldest["rank"],
        "skew_ratio": round(hottest["tokens_per_sec"] / cold_tps, 4)
        if cold_tps > 0 else None,
        "hottest_share": round(hottest["tokens_per_sec"] / fleet_tps, 4)
        if fleet_tps > 0 else None,
        "fleet_tokens_per_sec": round(fleet_tps, 3),
    }


def merge_gang_dir(directory: str) -> dict:
    """One-call report over a gang workdir: find rank files, merge, build
    the phase table, skew report, and the comms/ingest/serving/fleet
    rollups."""
    paths = find_rank_files(directory)
    events = merge_rank_files(paths)
    table = phase_table(events)
    return {
        "artifact": "telemetry_report",
        "directory": os.path.abspath(directory),
        "ranks": sorted(paths),
        "event_count": len(events),
        "phases": table,
        "skew": skew_report(table),
        "comms": comms_report(events, table),
        "ingest": ingest_report(events, table),
        "serving": serving_report(events, table),
        "requests": request_report(events),
        "fleet": fleet_report(events),
    }


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v * 1e3:.3f}" if v < 10 else f"{v:.3f}"
    return str(v)


def render_markdown(report: dict) -> str:
    """Human-readable form of ``merge_gang_dir``'s output: a per-phase
    p50/p99 table (durations in ms) and the rank-skew table."""
    lines = ["# Telemetry report", ""]
    lines.append(f"- ranks: {report['ranks']}")
    lines.append(f"- events merged: {report['event_count']}")
    lines += ["", "## Per-phase durations (ms)", ""]
    lines.append("| phase | rank | count | mean | p50 | p99 | max |")
    lines.append("|---|---|---|---|---|---|---|")
    for phase, entry in report["phases"].items():
        o = entry["overall"]
        lines.append(
            f"| {phase} | all | {o['count']} | {_fmt(o['mean'])} "
            f"| {_fmt(o['p50'])} | {_fmt(o['p99'])} | {_fmt(o['max'])} |"
        )
        for rank, s in entry["ranks"].items():
            lines.append(
                f"| {phase} | {rank} | {s['count']} | {_fmt(s['mean'])} "
                f"| {_fmt(s['p50'])} | {_fmt(s['p99'])} | {_fmt(s['max'])} |"
            )
    skew = report.get("skew") or {}
    lines += ["", "## Rank skew (straggler attribution)", ""]
    if skew:
        lines.append(
            "| phase | slowest rank | fastest rank | skew ratio | spread (ms) |"
        )
        lines.append("|---|---|---|---|---|")
        for phase, s in skew.items():
            ratio = s["skew_ratio"]
            lines.append(
                f"| {phase} | {s['slowest_rank']} | {s['fastest_rank']} "
                f"| {ratio if ratio is not None else '-'} "
                f"| {_fmt(s['spread'])} |"
            )
    else:
        lines.append("(no phase seen on more than one rank)")
    comms = report.get("comms") or {}
    if comms.get("counters") or comms.get("collectives"):
        lines += ["", "## Comms", ""]
        if comms.get("verdict"):
            lines.append(
                f"- verdict: **{comms['verdict']}** "
                f"(exposed-comms fraction {comms['comms_fraction']})"
            )
            lines.append("")
        if comms.get("overlap"):
            ov = comms["overlap"]
            lines.append(
                f"- overlap: {ov['bytes_overlapped']} bytes hidden behind "
                f"compute, {ov['bytes_exposed']} exposed "
                f"(overlapped fraction {ov['overlapped_fraction']})"
            )
            lines.append("")
        if comms.get("counters"):
            lines.append("| counter | rank | total bytes | steps | bytes/step |")
            lines.append("|---|---|---|---|---|")
            for name, per_rank in comms["counters"].items():
                for rank, entry in per_rank.items():
                    per_step = entry.get("per_step")
                    lines.append(
                        f"| {name} | {rank} | {int(entry['total'])} "
                        f"| {entry['steps'] or '-'} "
                        f"| {per_step if per_step is not None else '-'} |"
                    )
        if comms.get("collectives"):
            lines.append("")
            lines.append("| collective | rank | count | mean | p50 | p99 |")
            lines.append("|---|---|---|---|---|---|")
            for phase, entry in comms["collectives"].items():
                o = entry["overall"]
                lines.append(
                    f"| {phase} | all | {o['count']} | {_fmt(o['mean'])} "
                    f"| {_fmt(o['p50'])} | {_fmt(o['p99'])} |"
                )
                for rank, s in entry["ranks"].items():
                    lines.append(
                        f"| {phase} | {rank} | {s['count']} | {_fmt(s['mean'])} "
                        f"| {_fmt(s['p50'])} | {_fmt(s['p99'])} |"
                    )
    ingest = report.get("ingest") or {}
    if (
        ingest.get("phases")
        or ingest.get("buffer_occupancy")
        or ingest.get("counters")
    ):
        lines += ["", "## Ingest (data.*)", ""]
        if ingest.get("verdict"):
            lines.append(
                f"- verdict: **{ingest['verdict']}** "
                f"(stall fraction {ingest['stall_fraction']})"
            )
            lines.append("")
        if ingest.get("phases"):
            lines.append("| stage | rank | count | mean | p50 | p99 | max |")
            lines.append("|---|---|---|---|---|---|---|")
            for phase, entry in ingest["phases"].items():
                o = entry["overall"]
                lines.append(
                    f"| {phase} | all | {o['count']} | {_fmt(o['mean'])} "
                    f"| {_fmt(o['p50'])} | {_fmt(o['p99'])} | {_fmt(o['max'])} |"
                )
                for rank, s in entry["ranks"].items():
                    lines.append(
                        f"| {phase} | {rank} | {s['count']} | {_fmt(s['mean'])} "
                        f"| {_fmt(s['p50'])} | {_fmt(s['p99'])} | {_fmt(s['max'])} |"
                    )
        if ingest.get("buffer_occupancy"):
            lines.append("")
            lines.append(
                "| buffer occupancy | rank | samples | mean | p50 | p99 | max |"
            )
            lines.append("|---|---|---|---|---|---|---|")
            for rank, s in ingest["buffer_occupancy"].items():
                # Occupancies are batch counts, not durations — render raw.
                lines.append(
                    f"| data.buffer_occupancy | {rank} | {s['count']} "
                    f"| {s['mean']:.2f} | {s['p50']:g} | {s['p99']:g} "
                    f"| {s['max']:g} |"
                )
        if ingest.get("counters"):
            lines.append("")
            lines.append("| counter | rank | total |")
            lines.append("|---|---|---|")
            for name, per_rank in ingest["counters"].items():
                for rank, entry in per_rank.items():
                    lines.append(
                        f"| {name} | {rank} | {int(entry['total'])} |"
                    )
    serving = report.get("serving") or {}
    if serving.get("batches_by_mode") or serving.get("counters"):
        lines += ["", "## Serving", ""]
        if serving.get("padding_waste") is not None:
            lines.append(
                f"- padding waste: **{serving['padding_waste']}** of "
                "computed token slots"
            )
        for key in ("quarantines", "rejects", "expired"):
            if serving.get(key):
                lines.append(f"- {key}: {serving[key]}")
        if serving.get("batches_by_mode"):
            lines.append("")
            lines.append("| kv mode | dispatches | mean (ms) | total (s) |")
            lines.append("|---|---|---|---|")
            for mode, entry in serving["batches_by_mode"].items():
                lines.append(
                    f"| {mode} | {entry['count']} "
                    f"| {_fmt(entry['mean_s'])} | {entry['total_s']:.3f} |"
                )
        if serving.get("counters"):
            lines.append("")
            lines.append("| counter | rank | total |")
            lines.append("|---|---|---|")
            for name, per_rank in serving["counters"].items():
                for rank, entry in per_rank.items():
                    lines.append(
                        f"| {name} | {rank} | {int(entry['total'])} |"
                    )
    requests = report.get("requests") or {}
    if requests.get("breakdown"):
        lines += ["", "## Request latency breakdown (ms)", ""]
        if requests.get("by_prefill"):
            parts = ", ".join(
                f"{k}: {v}" for k, v in requests["by_prefill"].items()
            )
            lines.append(f"- prefill kinds: {parts}")
            lines.append("")
        lines.append("| component | count | mean | p50 | p99 | max |")
        lines.append("|---|---|---|---|---|---|")
        for field, s in requests["breakdown"].items():
            lines.append(
                f"| {field} | {s['count']} | {_fmt(s['mean'])} "
                f"| {_fmt(s['p50'])} | {_fmt(s['p99'])} | {_fmt(s['max'])} |"
            )
        if requests.get("slowest"):
            lines.append("")
            lines.append(
                "| slowest | rank | total | queue wait | ttft | launches "
                "| prefill |"
            )
            lines.append("|---|---|---|---|---|---|---|")
            for r in requests["slowest"]:
                lines.append(
                    f"| {r.get('trace_id') or '-'} | {r.get('rank')} "
                    f"| {_fmt(r.get('total_s'))} "
                    f"| {_fmt(r.get('queue_wait_s'))} "
                    f"| {_fmt(r.get('ttft_s'))} "
                    f"| {r.get('launches') if r.get('launches') is not None else '-'} "
                    f"| {r.get('prefill') or '-'} |"
                )
    fleet = report.get("fleet") or {}
    if fleet.get("requests"):
        lines += ["", "## Fleet (routed requests)", ""]
        parts = ", ".join(
            f"{k}: {v}" for k, v in fleet["by_outcome"].items()
        )
        lines.append(
            f"- routed: {fleet['requests']} requests "
            f"({parts}; {fleet['retries']} retries)"
        )
        if fleet.get("by_tier"):
            tiers = ", ".join(
                f"{k}: {v}" for k, v in fleet["by_tier"].items()
            )
            lines.append(f"- tiers: {tiers}")
        if fleet.get("per_replica"):
            lines.append("")
            lines.append("| replica | requests | mean (ms) | p50 | p99 |")
            lines.append("|---|---|---|---|---|")
            for rank, entry in fleet["per_replica"].items():
                s = entry.get("latency") or {}
                lines.append(
                    f"| {rank} | {entry['requests']} "
                    f"| {_fmt(s.get('mean'))} | {_fmt(s.get('p50'))} "
                    f"| {_fmt(s.get('p99'))} |"
                )
    return "\n".join(lines) + "\n"


def render_status_markdown(rows: list[dict]) -> str:
    """Live gang-status table for ``tools/gang_status.py``: one row per
    rank, from scraped /healthz + /statusz payloads (plus heartbeat
    sidecar enrichment). Each row dict may carry: rank, status, phase,
    step, heartbeat_age_s, queue_depth, tokens_per_sec, in_flight,
    occupancy, port."""
    lines = ["# Gang status", ""]
    lines.append(
        "| rank | status | phase | step | beat age (s) | queue "
        "| in flight | tok/s | kv occ | port |"
    )
    lines.append("|---|---|---|---|---|---|---|---|---|---|")

    def cell(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.3f}" if v < 100 else f"{v:.1f}"
        return str(v)

    for r in sorted(rows, key=lambda r: (r.get("rank") is None, r.get("rank"))):
        lines.append(
            f"| {cell(r.get('rank'))} | {cell(r.get('status'))} "
            f"| {cell(r.get('phase'))} | {cell(r.get('step'))} "
            f"| {cell(r.get('heartbeat_age_s'))} "
            f"| {cell(r.get('queue_depth'))} | {cell(r.get('in_flight'))} "
            f"| {cell(r.get('tokens_per_sec'))} | {cell(r.get('occupancy'))} "
            f"| {cell(r.get('port'))} |"
        )
    steps = [r.get("step") for r in rows if isinstance(r.get("step"), (int, float))]
    if len(steps) > 1:
        lines.append("")
        lines.append(f"- step skew (max - min): {max(steps) - min(steps):g}")
    return "\n".join(lines) + "\n"


__all__ = [
    "COMMS_BOUND_THRESHOLD",
    "INPUT_BOUND_THRESHOLD",
    "REQUEST_REPORT_SLOWEST",
    "comms_report",
    "find_rank_files",
    "fleet_report",
    "ingest_report",
    "load_jsonl",
    "merge_gang_dir",
    "merge_rank_files",
    "phase_table",
    "rank_file_name",
    "render_markdown",
    "render_status_markdown",
    "replica_skew",
    "request_report",
    "serving_report",
    "skew_report",
    "write_rank_file",
]
