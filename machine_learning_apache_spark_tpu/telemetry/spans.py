"""Nested trace spans over the telemetry event log.

A span is a named, timed region: entering emits a ``span_start`` event,
leaving emits a ``span_end`` carrying the duration. Nesting is tracked
per-thread (a thread-local stack), so a ``train.step`` span opened inside
a ``train.epoch`` span records its parent id and the merged timeline
reconstructs the tree. The serving engine's decode thread and the
launcher's monitor thread each get their own stack — spans never
interleave across threads.

Zero-cost-when-disabled: ``span()`` returns one module-level no-op
context manager when telemetry is off — no object allocation per step,
no branches beyond a cached boolean.

This module is also the home of the repo's original timing vocabulary:
``Timer`` and ``timed_span`` moved here from ``utils.timing`` (which
re-exports them for back-compat). ``timed_span`` keeps its printed
``<label>: <sec> sec`` line and now additionally emits a span event when
telemetry is enabled, so ad-hoc timings land on the same timeline as
structured instrumentation.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import threading
import time
from dataclasses import dataclass, field

from machine_learning_apache_spark_tpu.telemetry import events as _events

# CPython's GIL makes next() on a shared count atomic — no lock needed.
_SPAN_IDS = itertools.count(1)

_TLS = threading.local()


def _stack() -> list:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def current_span_id() -> int | None:
    """Id of the innermost open span on this thread, or None."""
    s = getattr(_TLS, "stack", None)
    return s[-1] if s else None


class _Span:
    """One open span. Context manager; re-entrant use is a bug (one span,
    one region)."""

    __slots__ = ("name", "attrs", "id", "parent", "_t0")

    def __init__(self, name: str, attrs: dict | None):
        self.name = name
        self.attrs = attrs
        self.id = next(_SPAN_IDS)
        self.parent: int | None = None
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        stack = _stack()
        self.parent = stack[-1] if stack else None
        stack.append(self.id)
        self._t0 = time.perf_counter()
        _events.get_log().emit(
            "span_start", self.name,
            span=self.id, parent=self.parent, attrs=self.attrs,
        )
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter() - self._t0
        stack = _stack()
        # Pop back to (and including) this span: tolerates a leaked inner
        # span rather than corrupting every later parent attribution.
        while stack and stack[-1] != self.id:
            stack.pop()
        if stack:
            stack.pop()
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs or ())
            attrs["error"] = exc_type.__name__
        _events.get_log().emit(
            "span_end", self.name,
            span=self.id, parent=self.parent, value=dur, attrs=attrs,
        )


class _NoopSpan:
    """Disabled-mode span: a single module-level instance, nothing per call."""

    __slots__ = ()
    name = ""
    id = None
    parent = None
    attrs = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


NOOP_SPAN = _NoopSpan()


def span(name: str, **attrs):
    """``with span("train.step", step=i):`` — time a region onto the event
    log. Returns the shared no-op when telemetry is disabled."""
    if not _events.enabled():
        return NOOP_SPAN
    return _Span(name, attrs or None)


def traced(name: str | None = None, **attrs):
    """Decorator form: ``@traced("serving.submit")`` (or bare ``@traced()``
    to use the function's qualified name)."""

    def deco(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _events.enabled():
                return fn(*args, **kwargs)
            with _Span(span_name, attrs or None):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# -- the repo's original timing vocabulary, re-homed ---------------------------


@dataclass
class Timer:
    """Start/stop wall-clock timer with rolling-span support.

    The reference repo self-times every training run with ``time.time()``
    pairs (19 sites; e.g. ``pytorch_multilayer_perceptron.py:98,118-120``)
    plus a rolling per-100-batch span
    (``pytorch_machine_translator.py:150,199-205``). This dataclass is the
    one structured implementation of that vocabulary; it stays pure
    (no event emission) so hot loops can lap it freely.
    """

    name: str = "train"
    _start: float = field(default_factory=time.perf_counter, repr=False)
    elapsed: float = 0.0

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self._start
        return self.elapsed

    def lap(self) -> float:
        """Elapsed since last start/lap; restarts the span (the reference's
        rolling 100-batch timer, ``pytorch_machine_translator.py:199-205``)."""
        now = time.perf_counter()
        span_ = now - self._start
        self._start = now
        return span_


@contextlib.contextmanager
def timed_span(label: str, emit=None):
    """``with timed_span("Training Time"):`` — prints ``<label>: <sec>`` on
    exit, the reference's universal metric line (SURVEY.md §6). When
    telemetry is enabled the region also lands on the event log as a span."""
    ctx = span(label)
    t = Timer(label).start()
    try:
        with ctx:
            yield t
    finally:
        t.stop()
        (emit or print)(f"{label}: {t.elapsed:.3f} sec")


__all__ = [
    "NOOP_SPAN",
    "Timer",
    "current_span_id",
    "span",
    "timed_span",
    "traced",
]
