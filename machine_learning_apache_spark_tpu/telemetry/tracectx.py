"""Distributed trace context — one id for a request's whole life.

The span layer (``telemetry.spans``) is strictly per-process: span ids
are thread-local integers, meaningful only inside one rank. This module
adds the cross-process half: a **trace context** — 128-bit trace id +
64-bit span id, W3C ``traceparent``-compatible — that travels with a
request across the router → replica → engine hops, so the gang-merged
view (``telemetry.traceview``) can stitch every process's spans into one
timeline.

Model (deliberately small):

- :func:`mint` creates a fresh sampled context — or ``None`` when
  tracing is off (``MLSPARK_TRACE=0``), telemetry is off, or the
  head-based sampler (``MLSPARK_TRACE_SAMPLE``, default 1.0) says no.
  "No context" is the zero-cost path: nothing downstream stamps
  anything.
- :func:`use` activates a context on the current thread for a ``with``
  block; every event emitted inside (spans, counters, annotations)
  carries ``trace=<trace_id>``. ``use(None)`` is a no-op passthrough,
  so call sites never branch on sampling.
- :func:`child` derives a new span id under the same trace — one per
  dispatch attempt, so retries land as distinct cross-process edges.
- :func:`to_traceparent` / :func:`parse_traceparent` are the wire codec
  (``00-<32 hex trace>-<16 hex span>-<2 hex flags>``). The parser is
  garbage-tolerant: anything malformed yields ``None``, never an
  exception — a bad header must not fail a request.

Head-based sampling is the overhead story: the decision is made once at
``mint`` and inherited by every hop, so an unsampled request pays one
RNG draw and nothing else (BENCH_SERVE_r06 pins the sampled-path cost).

stdlib-only, like every telemetry module. The thread-local slot itself
lives in ``telemetry.events`` so ``EventLog.emit`` can stamp events
without a circular import.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from dataclasses import dataclass

from machine_learning_apache_spark_tpu.telemetry import events as _events

ENV_TRACE = "MLSPARK_TRACE"
ENV_TRACE_SAMPLE = "MLSPARK_TRACE_SAMPLE"

#: Values read as "off" — mirrors utils.env.FALSY (not imported: telemetry
#: stays stdlib-only and cycle-free).
_FALSY = ("0", "false", "off", "no", "")

_HEX = frozenset("0123456789abcdef")

_STATE_LOCK = threading.Lock()
_ENABLED: bool | None = None  # guarded-by: _STATE_LOCK
_SAMPLE: float | None = None  # guarded-by: _STATE_LOCK

#: Trace/span id randomness. A private Random instance (urandom-seeded)
#: so recipe code seeding the global ``random`` module for reproducible
#: data cannot make two requests share a trace id.
_RNG = random.Random()


@dataclass(frozen=True)
class TraceContext:
    """One hop's identity within a distributed trace: the shared 128-bit
    ``trace_id`` (32 lowercase hex), this hop's ``span_id`` (16 lowercase
    hex), and the W3C flags byte (bit 0 = sampled)."""

    trace_id: str
    span_id: str
    flags: int = 1

    @property
    def sampled(self) -> bool:
        return bool(self.flags & 1)


def new_trace_id() -> str:
    """A fresh non-zero 128-bit trace id, 32 lowercase hex chars."""
    while True:
        tid = f"{_RNG.getrandbits(128):032x}"
        if tid != "0" * 32:
            return tid


def new_span_id() -> str:
    """A fresh non-zero 64-bit span id, 16 lowercase hex chars."""
    while True:
        sid = f"{_RNG.getrandbits(64):016x}"
        if sid != "0" * 16:
            return sid


# -- the on/off and sampling knobs --------------------------------------------
def trace_enabled() -> bool:
    """Tracing is on unless ``MLSPARK_TRACE`` is falsy — and never on
    when telemetry itself is off (a trace nobody records is pure cost).
    The env parse is cached; ``reset()`` clears it."""
    global _ENABLED
    with _STATE_LOCK:
        if _ENABLED is None:
            # Direct read by design: telemetry is stdlib-only by contract
            # (utils.env would cycle); the name is still registered in
            # utils/env.py so the contract and docs cover it.
            # mlspark-lint: ok env-direct-read -- stdlib-only module
            value = os.environ.get(ENV_TRACE)
            _ENABLED = (
                value is None or value.strip().lower() not in _FALSY
            )
        enabled = _ENABLED
    return enabled and _events.enabled()


def sample_rate() -> float:
    """Head-sampling probability in [0, 1] (``MLSPARK_TRACE_SAMPLE``,
    default 1.0 — every request traced). Malformed values read as 1.0:
    a typo'd knob must not silently disable tracing."""
    global _SAMPLE
    with _STATE_LOCK:
        if _SAMPLE is None:
            # mlspark-lint: ok env-direct-read -- stdlib-only module
            value = os.environ.get(ENV_TRACE_SAMPLE)
            try:
                rate = 1.0 if value is None else float(value)
            except ValueError:
                rate = 1.0
            _SAMPLE = min(1.0, max(0.0, rate))
        return _SAMPLE


def reset() -> None:
    """Drop the cached env parses and any context leaked onto this
    thread — test hook, chained from ``telemetry.reset()``."""
    global _ENABLED, _SAMPLE
    with _STATE_LOCK:
        _ENABLED = None
        _SAMPLE = None
    _events.set_current_trace(None)


# -- minting and propagation --------------------------------------------------
def mint(*, sampled: bool | None = None) -> TraceContext | None:
    """A fresh root context for one request — or ``None`` when tracing
    is off or the head sampler declines (``sampled`` overrides the coin
    flip for tests and always-trace paths)."""
    if not trace_enabled():
        return None
    if sampled is None:
        rate = sample_rate()
        sampled = rate >= 1.0 or _RNG.random() < rate
    if not sampled:
        return None
    return TraceContext(new_trace_id(), new_span_id(), flags=1)


def child(ctx: TraceContext | None) -> TraceContext | None:
    """Same trace, fresh span id — one per dispatch attempt, so a retry
    is a distinct edge under the same trace. ``None`` passes through."""
    if ctx is None:
        return None
    return TraceContext(ctx.trace_id, new_span_id(), ctx.flags)


def current() -> TraceContext | None:
    """The context active on this thread, or None."""
    return _events.current_trace()


#: Unambiguous alias for the flat ``telemetry.*`` namespace re-export.
current_trace_context = current


@contextlib.contextmanager
def use(ctx: TraceContext | None):
    """Activate ``ctx`` on the current thread for the block: every event
    emitted inside carries its trace id. ``use(None)`` yields without
    touching thread state, so unsampled requests stay zero-cost."""
    if ctx is None:
        yield None
        return
    prev = _events.current_trace()
    _events.set_current_trace(ctx)
    try:
        yield ctx
    finally:
        _events.set_current_trace(prev)


# -- the wire codec -----------------------------------------------------------
def _hexfield(s: str, n: int) -> bool:
    return len(s) == n and all(c in _HEX for c in s)


def to_traceparent(ctx: TraceContext) -> str:
    """``00-<trace_id>-<span_id>-<flags>`` — the W3C traceparent form
    the router sends on ``POST /v1/generate``."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{ctx.flags & 0xFF:02x}"


def parse_traceparent(header: object) -> TraceContext | None:
    """Decode a traceparent header, tolerating garbage: any malformed,
    all-zero, or forbidden-version (``ff``) value yields ``None`` — a
    replica must serve the request either way."""
    if not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not _hexfield(version, 2) or version == "ff":
        return None
    if not _hexfield(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _hexfield(span_id, 16) or span_id == "0" * 16:
        return None
    if not _hexfield(flags, 2):
        return None
    return TraceContext(trace_id, span_id, int(flags, 16))


__all__ = [
    "ENV_TRACE",
    "ENV_TRACE_SAMPLE",
    "TraceContext",
    "child",
    "current",
    "current_trace_context",
    "mint",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "reset",
    "sample_rate",
    "to_traceparent",
    "trace_enabled",
    "use",
]
