"""Typed in-process event log — the substrate every telemetry signal rides.

One process, one bounded, lock-protected log of ``Event`` records. Every
other telemetry surface is a view over it: ``spans`` appends span
start/stop pairs, ``registry`` can annotate metric updates, the flight
``recorder`` dumps its tail at the moment of a failure, and ``aggregate``
merges the per-rank JSONL exports into a gang-wide timeline.

Design constraints (why this module looks the way it does):

- **stdlib-only.** The launcher's runner and the fault-injection layer
  touch telemetry before the JAX platform is settled; nothing here may
  import jax (or anything that does).
- **Bounded.** The log is a ring (``collections.deque`` with ``maxlen``):
  a week-long serving process must not grow without bound, and the
  newest events are exactly what a flight recorder wants anyway.
  ``dropped`` counts evictions so truncation is visible, never silent.
- **Zero-cost when disabled.** ``MLSPARK_TELEMETRY=0`` makes ``enabled()``
  False; every instrumentation point checks it first and the no-op path
  allocates nothing (module-level singletons, one boolean read).

Timestamps: ``ts`` is ``time.monotonic()`` (ordering/durations within a
process), ``wall`` is ``time.time()`` (rough cross-rank alignment in
merged reports — heartbeat files already rely on wall mtimes the same
way).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import os
import threading
import time

ENV_TELEMETRY = "MLSPARK_TELEMETRY"
ENV_TELEMETRY_DIR = "MLSPARK_TELEMETRY_DIR"
ENV_MAX_EVENTS = "MLSPARK_TELEMETRY_EVENTS"

#: The event vocabulary. Everything in the log is one of these.
KINDS = ("span_start", "span_end", "counter", "gauge", "annotation")

_DEFAULT_MAX_EVENTS = 65536


def _env_rank() -> int | None:
    """This process's gang rank (``MLSPARK_PROCESS_ID``), or None outside
    a gang — same convention as ``utils.faults``."""
    # Direct read by design: telemetry is stdlib-only by contract (module
    # docstring); utils.env pulls the jax-importing utils package and a
    # telemetry->utils import would also cycle through
    # utils.profiling->telemetry.spans. Names stay registered.
    # mlspark-lint: ok env-direct-read -- stdlib-only module, see above
    v = os.environ.get("MLSPARK_PROCESS_ID")
    try:
        return int(v) if v is not None else None
    except ValueError:
        return None


@dataclasses.dataclass
class Event:
    """One telemetry record. ``span``/``parent`` are span ids for the
    span_start/span_end pair; ``value`` carries counter increments, gauge
    levels, and span durations (seconds, on span_end); ``trace`` is the
    distributed trace id stamped when a ``telemetry.tracectx`` context
    was active on the emitting thread."""

    kind: str
    name: str
    ts: float
    wall: float
    rank: int | None
    pid: int
    span: int | None = None
    parent: int | None = None
    value: float | None = None
    attrs: dict | None = None
    trace: str | None = None

    def to_dict(self) -> dict:
        d = {
            "kind": self.kind,
            "name": self.name,
            "ts": round(self.ts, 6),
            "wall": round(self.wall, 6),
            "rank": self.rank,
            "pid": self.pid,
        }
        if self.span is not None:
            d["span"] = self.span
        if self.parent is not None:
            d["parent"] = self.parent
        if self.value is not None:
            d["value"] = self.value
        if self.attrs:
            d["attrs"] = self.attrs
        if self.trace is not None:
            d["trace"] = self.trace
        return d


# -- distributed-trace thread slot --------------------------------------------
# The active TraceContext lives HERE (not in tracectx) so ``emit`` can
# stamp events with one thread-local read and tracectx can import events
# without a cycle. ``telemetry.tracectx.use`` is the only writer.
_TRACE_TLS = threading.local()


def current_trace():
    """The TraceContext active on this thread, or None."""
    return getattr(_TRACE_TLS, "ctx", None)


def set_current_trace(ctx) -> None:
    """Install (or, with None, clear) this thread's active trace context
    — called by ``telemetry.tracectx.use``, not by instrumentation."""
    _TRACE_TLS.ctx = ctx


class EventLog:
    """Lock-protected bounded ring of ``Event``s with JSONL export."""

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events: collections.deque[Event] = collections.deque(
            maxlen=max_events
        )
        self.dropped = 0  # evicted-by-the-ring count (visible truncation)

    def emit(
        self,
        kind: str,
        name: str,
        *,
        span: int | None = None,
        parent: int | None = None,
        value: float | None = None,
        attrs: dict | None = None,
    ) -> Event:
        if kind not in KINDS:
            raise ValueError(f"unknown event kind {kind!r} (expected {KINDS})")
        ctx = getattr(_TRACE_TLS, "ctx", None)
        ev = Event(
            kind=kind,
            name=name,
            ts=time.monotonic(),
            wall=time.time(),
            rank=_env_rank(),
            pid=os.getpid(),
            span=span,
            parent=parent,
            value=value,
            attrs=attrs,
            trace=None if ctx is None else ctx.trace_id,
        )
        with self._lock:
            if len(self._events) == self.max_events:
                self.dropped += 1
            self._events.append(ev)
        return ev

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def snapshot(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> list[Event]:
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            return list(
                itertools.islice(
                    self._events, len(self._events) - n, len(self._events)
                )
            )

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def export_jsonl(self, path: str) -> int:
        """Write every buffered event as one JSON line; returns the count.
        Atomic replace so a killed process can't leave a half-file for the
        merge step to choke on."""
        events = self.snapshot()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev.to_dict()) + "\n")
        os.replace(tmp, path)
        return len(events)


class _NoopEventLog:
    """The disabled-mode stand-in: same surface, no storage, no allocation
    per call beyond the call itself."""

    max_events = 0
    dropped = 0

    def emit(self, *a, **kw) -> None:  # noqa: ARG002
        return None

    def __len__(self) -> int:
        return 0

    def snapshot(self) -> list:
        return []

    def tail(self, n: int) -> list:  # noqa: ARG002
        return []

    def clear(self) -> None:
        pass

    def export_jsonl(self, path: str) -> int:  # noqa: ARG002
        return 0


NOOP_LOG = _NoopEventLog()

# -- process-global state ------------------------------------------------------
_ENABLED: bool | None = None  # None = not yet read from the environment
_LOG: EventLog | None = None
_STATE_LOCK = threading.Lock()


def enabled() -> bool:
    """Is telemetry on? Defaults to ON; ``MLSPARK_TELEMETRY=0`` (or
    ``false``/``off``) turns every instrumentation point into a no-op.
    The env read is cached — instrumented hot paths pay one global load."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get(ENV_TELEMETRY, "1").strip().lower() not in (  # mlspark-lint: ok env-direct-read -- stdlib-only module, see _env_rank
            "0", "false", "off", "no",
        )
    return _ENABLED


def set_enabled(value: bool | None) -> None:
    """Override (or, with None, re-arm the env read of) the enabled flag —
    the test hook; production processes configure via the environment."""
    global _ENABLED
    _ENABLED = value


def get_log():
    """The process-global event log (``NOOP_LOG`` when disabled)."""
    global _LOG
    if not enabled():
        return NOOP_LOG
    if _LOG is None:
        with _STATE_LOCK:
            if _LOG is None:
                try:
                    max_events = int(
                        os.environ.get(ENV_MAX_EVENTS, _DEFAULT_MAX_EVENTS)  # mlspark-lint: ok env-direct-read -- stdlib-only module, see _env_rank
                    )
                except ValueError:
                    max_events = _DEFAULT_MAX_EVENTS
                _LOG = EventLog(max_events=max_events)
    return _LOG


def reset() -> None:
    """Drop all global telemetry state (log, enabled cache, beacon) —
    test hook, also re-arms the env reads for a forked/spawned child."""
    global _ENABLED, _LOG
    with _STATE_LOCK:
        _ENABLED = None
        _LOG = None
    with _BEACON_LOCK:
        _BEACON.clear()


def telemetry_dir() -> str | None:
    """Where rank exports and flight dumps land (``MLSPARK_TELEMETRY_DIR``);
    None means nothing is written to disk."""
    return os.environ.get(ENV_TELEMETRY_DIR) or None  # mlspark-lint: ok env-direct-read -- stdlib-only module, see _env_rank


def annotate(name: str, **attrs) -> None:
    """Point-in-time annotation event (no duration) — breadcrumbs for the
    flight recorder ("gang teardown begins", "quarantining batch 7")."""
    if not enabled():
        return
    get_log().emit("annotation", name, attrs=attrs or None)


# -- the process beacon --------------------------------------------------------
# A tiny "what am I doing right now" dict (phase, step, http_port, ...)
# that long-running loops update and liveness surfaces read: the runner's
# heartbeat thread folds it into each beat's JSON payload (via a
# sys.modules peek — no import), and /healthz reports its age. Always on,
# independent of the enabled() flag: it is liveness state, not telemetry
# (one dict update under a lock, no thread, no ring growth).
_BEACON_LOCK = threading.Lock()
_BEACON: dict = {}


def beacon_update(**fields) -> None:
    """Merge ``fields`` into the beacon and stamp the update time
    (``ts`` monotonic, ``wall`` wall-clock)."""
    with _BEACON_LOCK:
        _BEACON.update(fields)
        _BEACON["ts"] = time.monotonic()
        _BEACON["wall"] = time.time()


def beacon() -> dict:
    """A copy of the current beacon ({} before any update)."""
    with _BEACON_LOCK:
        return dict(_BEACON)


__all__ = [
    "ENV_MAX_EVENTS",
    "ENV_TELEMETRY",
    "ENV_TELEMETRY_DIR",
    "Event",
    "EventLog",
    "KINDS",
    "NOOP_LOG",
    "annotate",
    "beacon",
    "beacon_update",
    "current_trace",
    "enabled",
    "get_log",
    "reset",
    "set_current_trace",
    "set_enabled",
    "telemetry_dir",
]
