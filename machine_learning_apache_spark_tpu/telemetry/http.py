"""Live observability plane — per-process HTTP endpoints over telemetry.

PR 3's telemetry is post-hoc: JSONL exports, merged reports, flight
dumps — all readable only after the process is done (or dead). This
module answers "what is this replica doing *right now*": a background
stdlib HTTP server exposing the process's live state on four endpoints,
the per-replica signal a least-loaded router or an SRE dashboard scrapes
(Prometheus conventions on ``/metrics``, JSON everywhere else):

- ``/metrics`` — the registry's Prometheus text exposition plus any
  registered **live gauges** (serving queue depth, page-pool occupancy —
  values that exist as object state, not counters, and must be sampled
  at scrape time);
- ``/healthz`` — liveness + health checks: process uptime, beacon
  (heartbeat) age, and every registered health provider's verdict.
  HTTP 200 when all healthy, 503 when any check fails (the quarantine /
  dead-worker signal a load balancer ejects on);
- ``/statusz`` — one JSON snapshot of everything: build + MLSPARK_*
  config, registry summaries, health, and every registered status
  section (the serving engine contributes its ledger, page-pool stats,
  and slowest-request trace exemplars);
- ``/flightz`` — the event-ring tail, i.e. the flight recorder's view
  without waiting for a crash.

Env contract: ``MLSPARK_TELEMETRY_HTTP`` is the port (0 = ephemeral);
unset means no server and **zero threads**. ``MLSPARK_TELEMETRY=0``
wins over everything — the whole plane stays dark. On startup the bound
port is written to a ``http_rank<k>.json`` sidecar in the telemetry dir
(discovery for ``tools/gang_status.py``) and into the process beacon
(so heartbeat payloads carry it too).

stdlib-only, like every telemetry module: importable before the JAX
platform is settled. Providers are called from scrape threads — they
must be thread-safe and non-blocking (every registered callable is
guarded; a raising provider becomes an ``"error"`` section, never a
dead endpoint).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from machine_learning_apache_spark_tpu.telemetry import events as _events
from machine_learning_apache_spark_tpu.telemetry import registry as _registry

ENV_TELEMETRY_HTTP = "MLSPARK_TELEMETRY_HTTP"

#: How many trailing events ``/flightz`` returns (same order of magnitude
#: as a flight dump; ``?n=`` overrides up to the ring size).
FLIGHTZ_TAIL = 256

SIDECAR_RE = re.compile(r"http_rank(\d+)\.json$")

_STATE_LOCK = threading.Lock()
_SERVER: "TelemetryHTTPServer | None" = None  # guarded-by: _STATE_LOCK
_STARTED_AT = time.monotonic()

# Provider registries (shared across the process, like the metrics
# registry): name -> zero-arg callable. Status providers return a JSON-able
# dict (one /statusz section each); health providers return a dict whose
# "healthy" key drives the /healthz verdict; gauge providers return a float
# sampled per /metrics scrape, keyed by full Prometheus metric name.
# Scrape paths copy the dict under the lock, then call providers unlocked.
_STATUS_PROVIDERS: dict[str, Callable[[], dict]] = {}  # guarded-by: _STATE_LOCK
_HEALTH_PROVIDERS: dict[str, Callable[[], dict]] = {}  # guarded-by: _STATE_LOCK
_GAUGE_PROVIDERS: dict[str, Callable[[], float]] = {}  # guarded-by: _STATE_LOCK


# -- provider registration -----------------------------------------------------
def register_status_provider(name: str, fn: Callable[[], dict]) -> None:
    """Contribute a ``/statusz`` section: ``fn()`` -> JSON-able dict,
    called at scrape time. Re-registering a name replaces it (engines are
    sequential within a process; last one wins)."""
    with _STATE_LOCK:
        _STATUS_PROVIDERS[name] = fn


def register_health_provider(name: str, fn: Callable[[], dict]) -> None:
    """Contribute a ``/healthz`` check: ``fn()`` -> dict with a boolean
    ``"healthy"`` key (absent counts as healthy). Any unhealthy check
    turns the endpoint 503."""
    with _STATE_LOCK:
        _HEALTH_PROVIDERS[name] = fn


def register_live_gauge(
    scope: str, name: str, fn: Callable[[], float]
) -> str:
    """Contribute a gauge sampled at every ``/metrics`` scrape (for state
    that lives in objects, not counters: queue depth, pool occupancy).
    Returns the full Prometheus metric name used."""
    full = _registry._sanitize(f"mlspark_{scope}_{name}")
    with _STATE_LOCK:
        _GAUGE_PROVIDERS[full] = fn
    return full


def unregister_provider(name: str) -> None:
    """Drop a status/health provider and any ``mlspark_<name>_*`` live
    gauges (engine stop path)."""
    prefix = _registry._sanitize(f"mlspark_{name}_")
    with _STATE_LOCK:
        _STATUS_PROVIDERS.pop(name, None)
        _HEALTH_PROVIDERS.pop(name, None)
        for key in [k for k in _GAUGE_PROVIDERS if k.startswith(prefix)]:
            del _GAUGE_PROVIDERS[key]


# -- endpoint payloads (plain functions: testable without a socket) ------------
def metrics_text() -> str:
    """``/metrics`` body: registry exposition + live gauge samples."""
    text = _registry.get_registry().to_prometheus_text()
    rank = _events._env_rank()
    labels = f'{{rank="{rank}"}}' if rank is not None else ""
    with _STATE_LOCK:
        gauges = dict(_GAUGE_PROVIDERS)
    lines: list[str] = []
    for full, fn in sorted(gauges.items()):
        try:
            value = float(fn())
        except Exception:  # noqa: BLE001 — one bad gauge must not kill the scrape
            continue
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full}{labels} {value:g}")
    return text + ("\n".join(lines) + "\n" if lines else "")


def healthz() -> tuple[dict, bool]:
    """``/healthz`` payload and overall verdict. Always includes process
    liveness basics; health providers add their checks."""
    beacon = _events.beacon()
    now = time.monotonic()
    heartbeat_age = (
        round(now - beacon["ts"], 3) if beacon.get("ts") is not None else None
    )
    checks: dict[str, dict] = {}
    with _STATE_LOCK:
        providers = dict(_HEALTH_PROVIDERS)
    healthy = True
    for name, fn in sorted(providers.items()):
        try:
            check = dict(fn())
        except Exception as e:  # noqa: BLE001 — a raising check is an unhealthy check
            check = {"healthy": False, "error": repr(e)}
        checks[name] = check
        healthy = healthy and bool(check.get("healthy", True))
    payload = {
        "status": "ok" if healthy else "degraded",
        "pid": os.getpid(),
        "rank": _events._env_rank(),
        "uptime_s": round(now - _STARTED_AT, 3),
        "heartbeat_age_s": heartbeat_age,
        "phase": beacon.get("phase"),
        "step": beacon.get("step"),
        "checks": checks,
    }
    return payload, healthy


def statusz() -> dict:
    """``/statusz`` payload: the one-stop JSON snapshot."""
    health, _ = healthz()
    payload = {
        "artifact": "statusz",
        "pid": os.getpid(),
        "rank": _events._env_rank(),
        "wall": round(time.time(), 3),
        "uptime_s": round(time.monotonic() - _STARTED_AT, 3),
        "build": _build_info(),
        "config": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith("MLSPARK_")
        },
        "beacon": _events.beacon(),
        "health": health,
        "registry": _registry.get_registry().snapshot(),
        "sections": {},
    }
    with _STATE_LOCK:
        providers = dict(_STATUS_PROVIDERS)
    for name, fn in sorted(providers.items()):
        try:
            payload["sections"][name] = fn()
        except Exception as e:  # noqa: BLE001 — one bad section, not a dead endpoint
            payload["sections"][name] = {"error": repr(e)}
    return payload


def flightz(n: int = FLIGHTZ_TAIL) -> dict:
    """``/flightz`` payload: the live event-ring tail."""
    log = _events.get_log()
    events = [ev.to_dict() for ev in log.tail(n)]
    return {
        "artifact": "flightz",
        "rank": _events._env_rank(),
        "pid": os.getpid(),
        "event_count": len(events),
        "dropped": log.dropped,
        "events": events,
    }


def tracez(trace_id: str | None = None) -> dict:
    """``/tracez`` payload: distributed-trace view over this process's
    live event ring — trace summaries, or one stitched tree with
    ``?id=<trace id>``. Single-process by nature (the ring is local);
    the cross-process merge is ``tools/trace_report.py``'s job."""
    # Lazy import: traceview pulls aggregate; the HTTP plane must stay
    # importable (and cheap) for processes that never serve a trace.
    from machine_learning_apache_spark_tpu.telemetry import (
        traceview as _traceview,
    )

    log = _events.get_log()
    events = [ev.to_dict() for ev in log.snapshot()]
    payload = _traceview.tracez_payload(events, trace_id)
    payload["rank"] = _events._env_rank()
    payload["pid"] = os.getpid()
    return payload


def _build_info() -> dict:
    info = {"python": sys.version.split()[0]}
    # sys.modules peek, never an import: /statusz must not be the thing
    # that drags jax into a process that deliberately hasn't loaded it.
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        info["jax"] = getattr(jax_mod, "__version__", None)
    return info


# -- the server ----------------------------------------------------------------
class _Handler(BaseHTTPRequestHandler):
    server_version = "mlspark-telemetry"

    def log_message(self, *args) -> None:  # noqa: ARG002 — scrapes aren't log spam
        pass

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                self._reply(200, metrics_text(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                payload, healthy = healthz()
                self._reply_json(200 if healthy else 503, payload)
            elif path in ("/statusz", "/"):
                self._reply_json(200, statusz())
            elif path == "/flightz":
                n = FLIGHTZ_TAIL
                m = re.search(r"(?:^|&)n=(\d+)", query)
                if m:
                    n = max(1, int(m.group(1)))
                self._reply_json(200, flightz(n))
            elif path == "/tracez":
                m = re.search(r"(?:^|&)id=([0-9a-fA-F]+)", query)
                self._reply_json(
                    200, tracez(m.group(1).lower() if m else None)
                )
            else:
                self._reply_json(404, {"error": f"no endpoint {path!r}"})
        except Exception:  # noqa: BLE001 — a scrape must never kill the thread
            self._reply_json(
                500, {"error": traceback.format_exc(limit=4)}
            )

    def _reply_json(self, code: int, payload: dict) -> None:
        self._reply(code, json.dumps(payload) + "\n", "application/json")

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-reply — its problem, not ours


class TelemetryHTTPServer:
    """One process's observability server: a ``ThreadingHTTPServer`` on a
    daemon thread (daemon handler threads too — scrapes never block
    process exit)."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self.sidecar_path: str | None = None
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mlspark-telemetry-http",
            daemon=True,
        )

    def start(self) -> "TelemetryHTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
        if self.sidecar_path:
            try:
                os.unlink(self.sidecar_path)
            except OSError:
                pass

    def url(self, path: str = "/") -> str:
        return f"http://127.0.0.1:{self.port}{path}"


# -- sidecar discovery ---------------------------------------------------------
def sidecar_name(rank: int) -> str:
    return f"http_rank{rank}.json"


def write_port_sidecar(
    port: int, directory: str | None = None, rank: int | None = None
) -> str | None:
    """Publish the bound port for discovery (``tools/gang_status.py``
    scans these): ``http_rank<k>.json`` in the telemetry dir. Returns the
    path, or None when no directory is configured."""
    d = directory or _events.telemetry_dir()
    if not d:
        return None
    if rank is None:
        r = _events._env_rank()
        rank = 0 if r is None else r
    path = os.path.join(d, sidecar_name(rank))
    payload = {
        "port": port,
        "rank": rank,
        "pid": os.getpid(),
        "wall": round(time.time(), 3),
    }
    try:
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def find_port_sidecars(directory: str) -> dict[int, dict]:
    """``{rank: sidecar payload}`` for every ``http_rank<k>.json`` in a
    directory (torn/unreadable files skipped)."""
    out: dict[int, dict] = {}
    for path in glob.glob(os.path.join(directory, "http_rank*.json")):
        m = SIDECAR_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(payload, dict) and "port" in payload:
            out[int(m.group(1))] = payload
    return dict(sorted(out.items()))


# -- process-global lifecycle --------------------------------------------------
def http_port_from_env() -> int | None:
    """The configured port, or None when the plane is off (unset, empty,
    or unparseable ``MLSPARK_TELEMETRY_HTTP``)."""
    # Direct read by design: telemetry is stdlib-only by contract;
    # utils.env would cycle via utils.profiling (see events._env_rank).
    # mlspark-lint: ok env-direct-read -- stdlib-only module, see above
    raw = os.environ.get(ENV_TELEMETRY_HTTP)
    if raw is None or not raw.strip():
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    return port if 0 <= port <= 65535 else None


def start_http_server(
    port: int | None = None,
    *,
    directory: str | None = None,
    rank: int | None = None,
) -> TelemetryHTTPServer | None:
    """Idempotently start the process-global server. With ``port=None``
    the env contract decides: no ``MLSPARK_TELEMETRY_HTTP`` -> no server,
    no thread. ``MLSPARK_TELEMETRY=0`` always means no server. Returns
    the (possibly pre-existing) server, or None when disabled."""
    global _SERVER
    if not _events.enabled():
        return None
    if port is None:
        port = http_port_from_env()
        if port is None:
            return None
    with _STATE_LOCK:
        if _SERVER is not None:
            return _SERVER
        server = TelemetryHTTPServer(port=port).start()
        # Sidecar before publication: once `_SERVER` is visible, a
        # concurrent stop_http_server() may swap it out and call
        # server.stop() — which unlinks `sidecar_path`. Assigning the
        # sidecar after publishing leaves a window where stop() sees
        # None and the file leaks past the server's death
        # (tests/test_analysis_races.py races start/stop on this).
        server.sidecar_path = write_port_sidecar(
            server.port, directory=directory, rank=rank
        )
        _SERVER = server
    # The beacon carries the port so heartbeat payloads double as
    # discovery when no telemetry dir is configured.
    _events.beacon_update(http_port=server.port)
    _events.annotate("telemetry.http_started", port=server.port)
    return server


def get_http_server() -> TelemetryHTTPServer | None:
    with _STATE_LOCK:
        return _SERVER


def stop_http_server() -> None:
    global _SERVER
    with _STATE_LOCK:
        server, _SERVER = _SERVER, None
    if server is not None:
        server.stop()


def reset() -> None:
    """Stop the server and drop every provider — test hook, called from
    ``telemetry.reset()``."""
    stop_http_server()
    with _STATE_LOCK:
        _STATUS_PROVIDERS.clear()
        _HEALTH_PROVIDERS.clear()
        _GAUGE_PROVIDERS.clear()


__all__ = [
    "ENV_TELEMETRY_HTTP",
    "FLIGHTZ_TAIL",
    "TelemetryHTTPServer",
    "find_port_sidecars",
    "flightz",
    "get_http_server",
    "healthz",
    "http_port_from_env",
    "metrics_text",
    "register_health_provider",
    "register_live_gauge",
    "register_status_provider",
    "reset",
    "sidecar_name",
    "start_http_server",
    "statusz",
    "stop_http_server",
    "tracez",
    "unregister_provider",
    "write_port_sidecar",
]
