"""Distributed trace stitching: per-rank event exports → request trees.

The tracing substrate leaves one breadcrumb trail per process: the
router's ``telemetry_rank<k>.jsonl`` (or a flight dump) holds
``fleet.submit``/``fleet.attempt`` spans, each replica's export holds its
``fleet.replica``/``serving.*`` spans, and every event carries the
128-bit ``trace`` id that ``tracectx`` stamped. This module is the read
side: merge those files, group by trace id, pair span_start/span_end
records, and rebuild each request's cross-process tree.

Two kinds of parent edge exist and both are honored:

- **in-process** — a span's ``parent`` field is the span id of the
  enclosing span on the same thread stack (same ``pid``);
- **cross-process** — the router's ``fleet.attempt`` span records the
  child span id it sent in the ``traceparent`` header as a ``ctx_span``
  attr, and the replica's ``fleet.replica`` span records the same id as
  ``remote_parent``. Matching the two joins the processes — and is the
  edge the Perfetto export draws a flow arrow over.

A trace is **complete** when it stitches into exactly one rooted tree
with no orphans (a span whose parent id cannot be resolved anywhere).
``completeness`` over a run's traces is the ``trace_complete`` gate the
serving bench pins.

Everything here is pure functions over plain dicts, stdlib-only (the
router process reads this without the framework imported).
"""

from __future__ import annotations

import glob
import json
import os

from machine_learning_apache_spark_tpu.telemetry import aggregate as _agg

#: Span attr names forming the cross-process edge (see module docstring).
CTX_SPAN_ATTR = "ctx_span"
REMOTE_PARENT_ATTR = "remote_parent"


# -- loading -------------------------------------------------------------------

def load_dir(directory: str) -> list[dict]:
    """Every event in a run directory: rank JSONL exports merged (rank
    stamped from the file name) plus any ``flight_*.json`` dumps, since a
    crashed process's only export is its flight recording. Events seen in
    both (the flight dump is a tail of the same log) are deduplicated on
    ``(pid, kind, name, ts, span)``."""
    events = _agg.merge_rank_files(_agg.find_rank_files(directory))
    seen = {
        (e.get("pid"), e.get("kind"), e.get("name"), e.get("ts"),
         e.get("span"))
        for e in events
    }
    for path in sorted(glob.glob(os.path.join(directory, "flight_*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue  # torn write — a flight dump is best-effort anyway
        rank = payload.get("rank")
        for ev in payload.get("events") or []:
            key = (ev.get("pid"), ev.get("kind"), ev.get("name"),
                   ev.get("ts"), ev.get("span"))
            if key in seen:
                continue
            seen.add(key)
            ev = dict(ev)
            if ev.get("rank") is None:
                ev["rank"] = rank
            events.append(ev)
    return events


# -- stitching -----------------------------------------------------------------

def _span_nodes(events: list[dict]) -> dict[tuple, dict]:
    """Pair span_start/span_end by ``(pid, span id)`` into node dicts.
    A span_end alone is enough (it carries parent, duration, and attrs);
    a span_start alone is a still-open span (dur_s None)."""
    nodes: dict[tuple, dict] = {}
    for ev in events:
        kind = ev.get("kind")
        if kind not in ("span_start", "span_end") or ev.get("span") is None:
            continue
        key = (ev.get("pid"), ev["span"])
        node = nodes.get(key)
        if node is None:
            node = nodes[key] = {
                "name": ev.get("name"),
                "span": ev["span"],
                "parent": ev.get("parent"),
                "pid": ev.get("pid"),
                "rank": ev.get("rank"),
                "trace": ev.get("trace"),
                "start_wall": None,
                "dur_s": None,
                "attrs": dict(ev.get("attrs") or {}),
                "children": [],
            }
        if kind == "span_start":
            node["start_wall"] = ev.get("wall")
        else:
            node["dur_s"] = ev.get("value")
            node["attrs"].update(ev.get("attrs") or {})
            if node["start_wall"] is None and ev.get("wall") is not None:
                # Flight tails can miss the start record; back-derive.
                node["start_wall"] = ev["wall"] - (ev.get("value") or 0.0)
        if ev.get("trace") and not node.get("trace"):
            node["trace"] = ev["trace"]
    return nodes


def assemble(events: list[dict]) -> dict[str, dict]:
    """``{trace_id: tree}`` over every traced span in ``events``. Each
    tree is ``{"trace_id", "roots": [node...], "orphans": [node...],
    "annotations": [event...], "span_count"}`` with nodes nested under
    ``children`` (in-process and resolved cross-process edges alike;
    remote children carry ``via: "remote"``)."""
    nodes = _span_nodes(events)
    by_trace: dict[str, list[dict]] = {}
    for node in nodes.values():
        if node.get("trace"):
            by_trace.setdefault(node["trace"], []).append(node)
    annotations: dict[str, list[dict]] = {}
    for ev in events:
        if ev.get("kind") == "annotation" and ev.get("trace"):
            annotations.setdefault(ev["trace"], []).append(ev)

    out: dict[str, dict] = {}
    for trace_id, tnodes in by_trace.items():
        by_key = {(n["pid"], n["span"]): n for n in tnodes}
        by_ctx_span = {
            n["attrs"][CTX_SPAN_ATTR]: n
            for n in tnodes
            if n["attrs"].get(CTX_SPAN_ATTR) is not None
        }
        roots: list[dict] = []
        orphans: list[dict] = []
        for n in sorted(
            tnodes, key=lambda n: (n.get("start_wall") or 0.0, n["span"])
        ):
            remote = n["attrs"].get(REMOTE_PARENT_ATTR)
            if remote is not None:
                attempt = by_ctx_span.get(remote)
                if attempt is not None:
                    n["via"] = "remote"
                    attempt["children"].append(n)
                else:
                    orphans.append(n)
                continue
            if n["parent"] is None:
                roots.append(n)
            elif (n["pid"], n["parent"]) in by_key:
                by_key[(n["pid"], n["parent"])]["children"].append(n)
            else:
                orphans.append(n)
        out[trace_id] = {
            "trace_id": trace_id,
            "roots": roots,
            "orphans": orphans,
            "annotations": annotations.get(trace_id, []),
            "span_count": len(tnodes),
        }
    return out


def trace_summary(tree: dict) -> dict:
    """One row per trace for the ``--slowest`` table: root span name and
    duration, span/process counts, completeness verdict."""
    roots = tree["roots"]
    root = roots[0] if roots else None
    pids = set()

    def _walk(n):
        pids.add(n["pid"])
        for c in n["children"]:
            _walk(c)

    for n in roots:
        _walk(n)
    for n in tree["orphans"]:
        pids.add(n["pid"])
    return {
        "trace_id": tree["trace_id"],
        "root": None if root is None else root["name"],
        "total_s": None if root is None else root["dur_s"],
        "spans": tree["span_count"],
        "processes": len(pids),
        "roots": len(roots),
        "orphans": len(tree["orphans"]),
        "complete": len(roots) == 1 and not tree["orphans"],
    }


def completeness(trees: dict[str, dict]) -> dict:
    """The ``trace_complete`` gate metric: the fraction of traces that
    stitch into exactly one rooted tree with zero orphans."""
    total = len(trees)
    complete = sum(
        1 for t in trees.values() if trace_summary(t)["complete"]
    )
    return {
        "traces": total,
        "complete": complete,
        "fraction": round(complete / total, 6) if total else None,
    }


def slowest(trees: dict[str, dict], n: int = 10) -> list[dict]:
    """The ``n`` slowest traces by root duration (undated roots last)."""
    rows = [trace_summary(t) for t in trees.values()]
    rows.sort(
        key=lambda r: (r["total_s"] is None, -(r["total_s"] or 0.0))
    )
    return rows[:n]


# -- Perfetto / Chrome trace-event export --------------------------------------

def _proc_key(ev: dict) -> int:
    """Perfetto row id: gang rank when stamped (small, stable, sorted
    first), else the OS pid (router / driver processes)."""
    rank = ev.get("rank")
    return int(rank) if rank is not None else int(ev.get("pid") or 0)


def _proc_name(ev: dict) -> str:
    rank = ev.get("rank")
    if rank is not None:
        return f"rank {rank}"
    return f"driver pid={ev.get('pid')}"


def perfetto_export(
    events: list[dict], trace_id: str | None = None
) -> dict:
    """Chrome ``trace_event`` JSON over ``events`` — load the returned
    dict (serialized) in Perfetto / ``chrome://tracing``.

    One process row per gang rank (driver/router processes row by OS
    pid); spans become ``ph:"X"`` complete slices on wall-clock
    microseconds; traced annotations become instants; ``counter`` events
    become ``ph:"C"`` tracks; and every resolved router→replica edge
    (``ctx_span`` == ``remote_parent``) becomes an ``s``/``f`` flow
    arrow, which is what makes a fanned-out request legible as one
    object in the UI.

    With ``trace_id`` the export narrows to that request's events; by
    default **all** spans ride along, so train.step / comms.* timelines
    land on the same view as the serving traces.
    """
    if trace_id is not None:
        events = [e for e in events if e.get("trace") == trace_id]
    nodes = _span_nodes(events)
    out: list[dict] = []
    procs: dict[int, str] = {}

    def _note_proc(ev: dict) -> int:
        pid = _proc_key(ev)
        if pid not in procs:
            procs[pid] = _proc_name(ev)
        return pid

    def _tid(node_or_ev: dict) -> int:
        # One thread row per trace within a process: requests render as
        # parallel tracks instead of interleaving on one line. Untraced
        # spans (train.step, the batcher) share track 0.
        t = node_or_ev.get("trace")
        return (int(t[:8], 16) & 0x3FFFFFFF) if t else 0

    for node in nodes.values():
        if node["start_wall"] is None:
            continue
        pid = _note_proc(node)
        ev_out = {
            "ph": "X",
            "name": node["name"],
            "pid": pid,
            "tid": _tid(node),
            "ts": node["start_wall"] * 1e6,
            "dur": (node["dur_s"] or 0.0) * 1e6,
            "cat": (node["name"] or "span").split(".")[0],
            "args": {**node["attrs"], "span": node["span"],
                     **({"trace": node["trace"]} if node["trace"] else {})},
        }
        out.append(ev_out)

    # Flow arrows over resolved cross-process edges.
    by_ctx_span = {
        n["attrs"][CTX_SPAN_ATTR]: n
        for n in nodes.values()
        if n["attrs"].get(CTX_SPAN_ATTR) is not None
    }
    for node in nodes.values():
        remote = node["attrs"].get(REMOTE_PARENT_ATTR)
        src = by_ctx_span.get(remote) if remote is not None else None
        if src is None or src["start_wall"] is None \
                or node["start_wall"] is None:
            continue
        common = {"cat": "trace", "name": "dispatch", "id": str(remote)}
        out.append({
            **common, "ph": "s", "pid": _proc_key(src), "tid": _tid(src),
            "ts": src["start_wall"] * 1e6,
        })
        out.append({
            **common, "ph": "f", "bp": "e", "pid": _proc_key(node),
            "tid": _tid(node), "ts": node["start_wall"] * 1e6,
        })

    for ev in events:
        kind = ev.get("kind")
        if kind == "annotation" and (trace_id is None or ev.get("trace")):
            pid = _note_proc(ev)
            out.append({
                "ph": "i", "s": "p", "name": ev.get("name"), "pid": pid,
                "tid": _tid(ev), "ts": (ev.get("wall") or 0.0) * 1e6,
                "cat": "annotation",
                "args": dict(ev.get("attrs") or {}),
            })
        elif kind == "counter" and trace_id is None:
            pid = _note_proc(ev)
            out.append({
                "ph": "C", "name": ev.get("name"), "pid": pid, "tid": 0,
                "ts": (ev.get("wall") or 0.0) * 1e6,
                "args": {"value": ev.get("value") or 0.0},
            })

    meta = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": name}}
        for pid, name in sorted(procs.items())
    ]
    meta += [
        {"ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
         "args": {"sort_index": i}}
        for i, pid in enumerate(sorted(procs))
    ]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


# -- live /tracez payload ------------------------------------------------------

def tracez_payload(events: list[dict], trace_id: str | None = None) -> dict:
    """The ``/tracez`` endpoint body: with ``trace_id``, that trace's
    full tree; without, a summary row per known trace (newest-rooted
    first is not guaranteed — callers sort client-side)."""
    trees = assemble(events)
    if trace_id is not None:
        tree = trees.get(trace_id)
        if tree is None:
            return {"artifact": "tracez", "trace_id": trace_id,
                    "error": "unknown trace id"}
        return {"artifact": "tracez", "trace_id": trace_id, **tree}
    return {
        "artifact": "tracez",
        "completeness": completeness(trees),
        "traces": [trace_summary(t) for t in trees.values()],
    }


__all__ = [
    "CTX_SPAN_ATTR",
    "REMOTE_PARENT_ATTR",
    "assemble",
    "completeness",
    "load_dir",
    "perfetto_export",
    "slowest",
    "trace_summary",
    "tracez_payload",
]
