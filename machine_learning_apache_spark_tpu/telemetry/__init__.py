"""telemetry/ — unified observability for the whole stack.

One subsystem every layer reports into, replacing four disconnected
islands (``utils.timing``, ``utils.profiling``, ``train.metrics``,
``serving.metrics``) with a single event substrate:

- :mod:`~.events` — lock-protected, bounded in-process event log
  (span start/stop, counter, gauge, annotation) with JSONL export;
- :mod:`~.spans` — nested trace spans (context manager + decorator),
  thread-local stacks, plus the re-homed ``Timer``/``timed_span``;
- :mod:`~.registry` — process-global counters/gauges/histograms with
  ``snapshot()`` and Prometheus text export;
- :mod:`~.aggregate` — merge per-rank ``telemetry_rank<k>.jsonl`` files
  into per-phase p50/p99 tables and a rank-skew (straggler) report;
- :mod:`~.recorder` — flight recorder: dump the last ~512 events to
  ``flight_<rank>.json`` at the moment of failure.

Configuration is environmental: ``MLSPARK_TELEMETRY=0`` turns every
entry point into a no-op singleton (zero per-step allocation);
``MLSPARK_TELEMETRY_DIR`` is where rank exports and flight dumps land.
All submodules are stdlib-only — safe to import before JAX platform
configuration (the launcher's runner does exactly that).

See docs/OBSERVABILITY.md for the event schema and workflows.
"""

from machine_learning_apache_spark_tpu.telemetry import (
    aggregate as _aggregate_mod,
)
from machine_learning_apache_spark_tpu.telemetry.aggregate import (
    merge_gang_dir,
    render_markdown,
    write_rank_file,
)
from machine_learning_apache_spark_tpu.telemetry import events as _events_mod
from machine_learning_apache_spark_tpu.telemetry import http as _http_mod
from machine_learning_apache_spark_tpu.telemetry import (
    registry as _registry_mod,
)
from machine_learning_apache_spark_tpu.telemetry.events import (
    ENV_TELEMETRY,
    ENV_TELEMETRY_DIR,
    Event,
    EventLog,
    annotate,
    beacon,
    beacon_update,
    enabled,
    get_log,
    set_enabled,
    telemetry_dir,
)
from machine_learning_apache_spark_tpu.telemetry.http import (
    ENV_TELEMETRY_HTTP,
    TelemetryHTTPServer,
    get_http_server,
    register_health_provider,
    register_live_gauge,
    register_status_provider,
    start_http_server,
    stop_http_server,
    unregister_provider,
)
from machine_learning_apache_spark_tpu.telemetry.recorder import (
    FLIGHT_CAPACITY,
    dump_flight,
    flight_path,
    load_flight,
)
from machine_learning_apache_spark_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)
from machine_learning_apache_spark_tpu.telemetry.spans import (
    Timer,
    current_span_id,
    span,
    timed_span,
    traced,
)
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as _tracectx_mod,
)
from machine_learning_apache_spark_tpu.telemetry.tracectx import (
    ENV_TRACE,
    ENV_TRACE_SAMPLE,
    TraceContext,
    current_trace_context,
    trace_enabled,
)


def reset() -> None:
    """Drop ALL process-global telemetry state (event log, registry,
    cached enabled flag, beacon, HTTP server + providers, trace-context
    caches) — test hook and fork/spawn re-arm."""
    _http_mod.reset()
    _tracectx_mod.reset()
    _events_mod.reset()
    _registry_mod.reset()
    _aggregate_mod.clear_parse_cache()

__all__ = [
    "ENV_TELEMETRY",
    "ENV_TELEMETRY_DIR",
    "ENV_TELEMETRY_HTTP",
    "ENV_TRACE",
    "ENV_TRACE_SAMPLE",
    "Event",
    "EventLog",
    "FLIGHT_CAPACITY",
    "MetricsRegistry",
    "TelemetryHTTPServer",
    "Timer",
    "TraceContext",
    "annotate",
    "beacon",
    "beacon_update",
    "current_span_id",
    "current_trace_context",
    "dump_flight",
    "enabled",
    "flight_path",
    "get_http_server",
    "get_log",
    "get_registry",
    "load_flight",
    "merge_gang_dir",
    "register_health_provider",
    "register_live_gauge",
    "register_status_provider",
    "render_markdown",
    "reset",
    "set_enabled",
    "span",
    "start_http_server",
    "stop_http_server",
    "telemetry_dir",
    "timed_span",
    "trace_enabled",
    "traced",
    "unregister_provider",
    "write_rank_file",
]
