"""Serving engine — the decode loop behind ``Translator.serve()``.

Wiring: caller threads tokenize and ``submit()`` into the admission
queue; one background worker pulls shape-bucketed batches from the
``Batcher``, takes KV slots for every member, pads the batch to the
bucket's static ``[max_batch, boundary]`` shape, and runs the compiled
cached decoder for that bucket. The eager path stays thin — tokenize,
pad, dispatch — and everything hot is an already-compiled XLA program
(the veScale split: request plumbing in Python, math in SPMD programs).

Shape discipline is the whole game: one jitted callable per bucket
boundary, batch always padded to ``max_batch`` (filler rows replicate
row 0 — valid tokens, so no all-masked softmax — and are discarded), so
``warmup()`` precompiles the complete program set and steady state runs
with zero recompiles. ``recompiles_after_warmup`` watches the jit caches
(via ``utils.compilation_cache``-style discipline, counted per callable)
and is the demo/bench acceptance gate.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Sequence

import jax
import numpy as np

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as _tracectx,
)
from machine_learning_apache_spark_tpu.data.text import EOS_ID, SOS_ID
from machine_learning_apache_spark_tpu.utils import env as envcfg
from machine_learning_apache_spark_tpu.serving.batcher import (
    Batch,
    Batcher,
    TokenBudgetBatcher,
)
from machine_learning_apache_spark_tpu.serving.kv_slots import KVSlotPool
from machine_learning_apache_spark_tpu.serving.metrics import ServingMetrics
from machine_learning_apache_spark_tpu.serving.queue import (
    DeadlineExceeded,
    RequestQueue,
    ServeRequest,
)
from machine_learning_apache_spark_tpu.utils.faults import maybe_fault
from machine_learning_apache_spark_tpu.utils.logging import get_logger
from machine_learning_apache_spark_tpu.utils.profiling import annotate

log = get_logger(__name__)


class EngineStopped(RuntimeError):
    """The engine shut down before this request completed."""


class InternalError(RuntimeError):
    """The engine failed this request internally (its decode batch raised).

    The failure is *contained*: only the quarantined batch's requests see
    this, the decode loop keeps serving, and — because the per-bucket
    programs were compiled at warmup — recovery triggers zero recompiles.
    The original exception rides along as ``__cause__``.
    """


class _HealthWindow:
    """The /healthz quarantine-recovery window, shared between the decode
    worker (writes) and HTTP scrape threads (reads). Both timestamps move
    under one lock so a reader always sees a (quarantine, ok-batch) pair
    that actually coexisted. The previous two-bare-loads read was pair-
    consistent only by accident of CPython's bytecode-level GIL switching
    (no call between the loads); any refactor inserting one — or a
    free-threaded build — could pair a fresh ok-batch time with a stale
    quarantine time and report "recovered" mid-degraded-window. The lock
    makes the guarantee structural; ``tests/test_analysis_races.py``
    hammers it from 4 threads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last_quarantine_t: float | None = None  # guarded-by: self._lock
        self._last_ok_batch_t: float | None = None  # guarded-by: self._lock

    def note_quarantine(self, t: float) -> None:
        with self._lock:
            self._last_quarantine_t = t

    def note_ok_batch(self, t: float) -> None:
        with self._lock:
            self._last_ok_batch_t = t

    def snapshot(self) -> tuple[float | None, float | None]:
        """A consistent (last_quarantine_t, last_ok_batch_t) pair."""
        with self._lock:
            return self._last_quarantine_t, self._last_ok_batch_t

    def recovered(self) -> bool:
        """False while the most recent quarantine has not yet been
        followed by a successful batch."""
        lq, lok = self.snapshot()
        return lq is None or (lok is not None and lok > lq)


class ServingEngine:
    """Continuous-batching server over a ``Translator``-shaped bundle
    (``model``, ``params``, ``src_pipe``, ``trg_pipe``).

    >>> with translator.serve(max_batch=8, boundaries=(16, 32)) as eng:
    ...     futs = [eng.submit(s) for s in texts]
    ...     outs = [f.result(timeout=30) for f in futs]

    Two KV disciplines share this front door (``kv_mode``, default
    ``"paged"``, env ``MLSPARK_SERVE_KV_MODE``):

    - **paged** — a page-table KV store and ONE compiled ragged decode
      program for any batch occupancy/length mix, chunk-padded prefill,
      refcounted prefix sharing, immediate FIFO admission;
    - **padded** — the legacy per-bucket rectangle programs, kept as the
      equivalence oracle (greedy outputs are token-identical) and the
      beam-search path.

    Tuning knobs (see docs/SERVING.md): ``boundaries`` bound prompt
    length (and pick the padded compile set), ``max_batch`` the padded
    batch shape, ``max_wait_s`` the padded co-batching patience,
    ``max_queue_depth`` the backpressure point; paged mode adds
    ``max_active`` (concurrent rows), ``page_size``/``num_pages`` (KV
    granularity/budget), ``prefill_chunk``+``prefill_budget`` (chunked-
    prefill pacing), ``steps_per_launch`` (decode steps per dispatch),
    ``prefix_cache_size`` (shared-prefix entries), and ``kv_dtype``
    (``"float32"`` default / ``"int8"`` quantized pages with per-page
    scales, env ``MLSPARK_SERVE_KV_DTYPE``; paged+greedy only —
    padded/beam engines reject int8 loudly).
    """

    def __init__(
        self,
        translator,
        *,
        boundaries: Sequence[int] = (16, 32, 64),
        max_batch: int = 8,
        max_wait_s: float = 0.02,
        max_queue_depth: int = 64,
        num_slots: int | None = None,
        max_new_tokens: int | None = None,
        default_deadline_s: float | None = None,
        method: str = "greedy",
        beam_size: int = 4,
        length_penalty: float = 0.6,
        kv_mode: str | None = None,
        kv_dtype: str | None = None,
        quantize_self: bool = False,
        page_size: int = 8,
        prefill_chunk: int | None = None,
        steps_per_launch: int = 4,
        max_active: int | None = None,
        num_pages: int | None = None,
        prefix_cache_size: int = 32,
        prefill_budget: int | None = None,
        clock=time.monotonic,
    ):
        cfg = translator.model.cfg
        boundaries = tuple(sorted(boundaries))
        if boundaries[-1] > cfg.max_len:
            raise ValueError(
                f"largest boundary {boundaries[-1]} exceeds the model's "
                f"max_len {cfg.max_len}; positions past max_len have no "
                "encoding"
            )
        if method not in ("greedy", "beam"):
            raise ValueError(
                f"method must be 'greedy' or 'beam', got {method!r}"
            )
        if kv_mode is None:
            kv_mode = envcfg.get_str("MLSPARK_SERVE_KV_MODE")
        if kv_mode not in ("padded", "paged"):
            raise ValueError(
                f"kv_mode must be 'padded' or 'paged', got {kv_mode!r} "
                "(check MLSPARK_SERVE_KV_MODE)"
            )
        if method == "beam" and kv_mode == "paged":
            # Beam search rides the dense flax-cache decoder (hypothesis
            # rows share and reorder KV); the paged store has no story
            # for that yet, so beam engines run the padded path.
            log.info("beam method: routing kv_mode paged -> padded")
            kv_mode = "padded"
        # Quantized KV store: arg > env > default, validated here like
        # kv_mode. int8 exists only for the paged store (the padded/beam
        # flax cache has no scale plane), so those combinations fail
        # loudly instead of silently serving fp32.
        if kv_dtype is None:
            kv_dtype = envcfg.get_str("MLSPARK_SERVE_KV_DTYPE")
        if kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'float32' or 'int8', got {kv_dtype!r} "
                "(check MLSPARK_SERVE_KV_DTYPE)"
            )
        if kv_dtype == "int8" and kv_mode != "paged":
            raise ValueError(
                "kv_dtype='int8' requires the paged KV store; this engine "
                f"resolved kv_mode={kv_mode!r}"
                + (" via method='beam'" if method == "beam" else "")
                + " — use kv_mode='paged' with greedy decoding, or drop "
                "the int8 request (check MLSPARK_SERVE_KV_DTYPE)"
            )
        self.kv_dtype = kv_dtype
        self.quantize_self = bool(quantize_self)
        self.translator = translator
        self.boundaries = boundaries
        self.max_batch = max_batch
        self.max_new_tokens = (
            cfg.max_len - 1 if max_new_tokens is None else max_new_tokens
        )
        self.method = method
        self.kv_mode = kv_mode
        self.clock = clock
        self.metrics = ServingMetrics(clock=clock)
        self.queue = RequestQueue(
            max_queue_depth, default_deadline_s=default_deadline_s,
            clock=clock, on_expire=self.metrics.on_expire,
            on_slo=self.metrics.on_slo,
        )
        self.batcher = Batcher(
            self.queue,
            boundaries=boundaries,
            max_batch=max_batch,
            max_wait_s=max_wait_s,
        )
        if kv_mode == "paged":
            from machine_learning_apache_spark_tpu.serving.paged_runtime import (
                PagedDecodeRuntime,
            )

            self.max_active = max_active or max_batch
            if prefill_chunk is None:
                prefill_chunk = max(
                    page_size, boundaries[0] // page_size * page_size
                )
            self.prefill_chunk = prefill_chunk
            # Chunked-prefill pacing: at most this many chunk-padded
            # prompt tokens prefill between consecutive decode launches,
            # so admission bursts can't stall in-flight rows' next token.
            self.prefill_budget = (
                prefill_budget
                if prefill_budget is not None
                else 2 * -(-boundaries[-1] // prefill_chunk) * prefill_chunk
            )
            self.runtime = PagedDecodeRuntime(
                translator.model, translator.params,
                max_active=self.max_active,
                max_src=boundaries[-1],
                max_new_tokens=self.max_new_tokens,
                page_size=page_size,
                prefill_chunk=prefill_chunk,
                steps_per_launch=steps_per_launch,
                num_pages=num_pages,
                prefix_cache_size=prefix_cache_size,
                kv_dtype=kv_dtype,
                quantize_self=quantize_self,
                sos_id=SOS_ID, eos_id=EOS_ID, pad_id=cfg.pad_id,
            )
            # The row pool: one slot = one cache row in the launch
            # program (``num_slots`` is a padded-path knob; the paged
            # in-flight ceiling is ``max_active``).
            self.pool = KVSlotPool(self.max_active)
            self.paged_batcher = TokenBudgetBatcher(
                self.queue, chunk=prefill_chunk
            )
        else:
            self.max_active = max_batch
            self.runtime = None
            # 2× max_batch by default: one batch decoding plus one forming.
            self.pool = KVSlotPool(num_slots or 2 * max_batch)
        self._decoders = {
            b: self._make_decoder(beam_size, length_penalty)
            for b in boundaries
        }
        self._compiles_at_warmup: int | None = None
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        # Monotonic sequence over dispatched batches/launches — the
        # ``decode_batch`` fault-injection coordinate (worker thread
        # only; no lock needed).
        self._batch_seq = 0
        # Health model for /healthz: the engine is DEGRADED while its most
        # recent quarantine is more recent than its most recent successful
        # batch — i.e. it has contained a fault and not yet proven it can
        # decode again. Worker-thread writes, scrape-thread reads.
        self._health = _HealthWindow()

    def _make_decoder(self, beam_size: int, length_penalty: float):
        """One jitted decode callable (its own jit cache → per-bucket
        compile counting stays exact)."""
        from machine_learning_apache_spark_tpu.models import (
            beam_translate,
            greedy_translate_cached,
        )

        model, mnt = self.translator.model, self.max_new_tokens
        if self.method == "beam":
            fn = lambda p, s: beam_translate(  # noqa: E731
                model, p, s, beam_size=beam_size,
                length_penalty=length_penalty, max_new_tokens=mnt,
                sos_id=SOS_ID, eos_id=EOS_ID,
            )
        else:
            fn = lambda p, s: greedy_translate_cached(  # noqa: E731
                model, p, s, max_new_tokens=mnt, sos_id=SOS_ID, eos_id=EOS_ID,
            )
        return jax.jit(fn)

    # -- lifecycle -----------------------------------------------------------
    def start(self, *, warmup: bool = True) -> "ServingEngine":
        if self._worker is not None:
            raise RuntimeError("engine already started")
        if warmup:
            self.warmup()
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._serve_loop, name="serving-engine", daemon=True
        )
        self._worker.start()
        # The live plane: contribute this engine's state to /statusz,
        # /healthz, and /metrics, and (idempotently) start the HTTP
        # server — a no-op with zero threads unless MLSPARK_TELEMETRY_HTTP
        # is set and telemetry is on.
        telemetry.register_status_provider("serving", self._status_snapshot)
        telemetry.register_health_provider("serving", self._health_snapshot)
        if self.runtime is not None:
            # First-class residency section for the fleet router's
            # affinity table: bounded MRU digests of the prompts whose
            # prefix KV this replica already holds (fleet/affinity.py
            # scrapes sections.prefix_cache off /statusz).
            telemetry.register_status_provider(
                "prefix_cache", self.runtime.prefix_cache.stats
            )
        telemetry.register_live_gauge(
            "serving", "queue_depth_live", lambda: self.queue.depth
        )
        if self.runtime is not None:
            telemetry.register_live_gauge(
                "serving", "kv_page_occupancy",
                lambda: self.runtime.mem_pool.occupancy,
            )
            telemetry.register_live_gauge(
                "serving", "kv_mem_bytes_in_use",
                lambda: self.runtime.mem_pool.bytes_in_use,
            )
            telemetry.register_live_gauge(
                "serving", "active_rows",
                lambda: self.runtime.active_count(),
            )
        telemetry.start_http_server()
        telemetry.beacon_update(phase="serving")
        return self

    def stop(self, *, timeout: float = 30.0) -> None:
        if self._worker is None:
            return
        telemetry.unregister_provider("serving")
        telemetry.unregister_provider("prefix_cache")
        self._stop.set()
        with self.queue.cond:
            self.queue.cond.notify_all()
        self._worker.join(timeout)
        self._worker = None
        n = self.queue.fail_all(EngineStopped("serving engine stopped"))
        if n:
            # Counted into ``failed`` so the conservation law balances
            # across shutdown: stop-drained requests are terminal too.
            self.metrics.on_failure(n)
            log.info("engine stop failed %d queued requests", n)

    def __enter__(self) -> "ServingEngine":
        if self._worker is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- warmup / compile accounting ----------------------------------------
    def warmup(self) -> int:
        """Precompile every program a live request could need — padded:
        one decoder per bucket; paged: one prefill per chunk count plus
        the single ragged launch — so no request ever pays a compile.
        Returns the program count."""
        if self.kv_mode == "paged":
            with annotate("serve_warmup_paged"):
                n = self.runtime.warmup()
            self._compiles_at_warmup = self.compile_count()
            log.info(
                "warmup compiled %d paged programs (%d prefill widths + 1 "
                "launch; max_active=%d, page_size=%d)",
                n, n - 1, self.max_active, self.runtime.page_size,
            )
            return n
        params = self.translator.params
        row = [SOS_ID, EOS_ID]
        for b in self.boundaries:
            src = np.full((self.max_batch, b), self._pad_id, np.int32)
            src[:, : len(row)] = row
            with annotate(f"serve_warmup_b{b}"):
                np.asarray(jax.block_until_ready(self._decoders[b](params, src)))
        self._compiles_at_warmup = self.compile_count()
        log.info(
            "warmup compiled %d bucket programs (max_batch=%d, buckets=%s)",
            len(self.boundaries), self.max_batch, list(self.boundaries),
        )
        return len(self.boundaries)

    def compile_count(self) -> int | None:
        """Total compiled programs across every jitted callable the
        engine owns — bucket decoders plus, in paged mode, the runtime's
        prefill/launch programs (None if the jax build doesn't expose
        the probe)."""
        from machine_learning_apache_spark_tpu.utils.compilation_cache import (
            jit_cache_size,
        )

        fns = list(self._decoders.values())
        if self.runtime is not None:
            fns += self.runtime.jit_fns()
        sizes = [jit_cache_size(f) for f in fns]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)

    @property
    def recompiles_after_warmup(self) -> int | None:
        """Programs compiled since ``warmup()`` — 0 in healthy steady
        state (the demo/bench acceptance gate)."""
        n = self.compile_count()
        if n is None or self._compiles_at_warmup is None:
            return None
        return n - self._compiles_at_warmup

    # -- live plane providers (called from HTTP scrape threads) --------------
    def _health_snapshot(self) -> dict:
        """/healthz check: worker thread alive, and not in the degraded
        window between a quarantine and the next successful batch."""
        worker = self._worker
        worker_alive = worker is not None and worker.is_alive()
        recovered = self._health.recovered()
        return {
            "healthy": worker_alive and recovered,
            "worker_alive": worker_alive,
            "quarantine_recovered": recovered,
            "kv_mode": self.kv_mode,
            "kv_dtype": self.kv_dtype,
            "queue_depth": self.queue.depth,
            "loop_restarts": self.metrics.loop_restarts,
            "quarantined": self.metrics.quarantined,
        }

    def _status_snapshot(self) -> dict:
        """/statusz section: the engine's full live state — config,
        conservation ledger, latency summary, page-pool stats, slowest-
        request exemplars."""
        out = {
            "kv_mode": self.kv_mode,
            "kv_dtype": self.kv_dtype,
            "method": self.method,
            "boundaries": list(self.boundaries),
            "max_batch": self.max_batch,
            "max_active": self.max_active,
            "max_new_tokens": self.max_new_tokens,
            "queue_depth": self.queue.depth,
            "recompiles_after_warmup": self.recompiles_after_warmup,
            "ledger": self.metrics.ledger(),
            "metrics": self.metrics.summary(),
            "slowest_requests": self.metrics.request_exemplars(),
        }
        if self.runtime is not None:
            out["page_pool"] = self.runtime.stats()
        return out

    # -- request path --------------------------------------------------------
    @property
    def _pad_id(self) -> int:
        return self.translator.model.cfg.pad_id

    def submit(
        self,
        text: str,
        *,
        deadline_s: float | None = None,
        tier: str | None = None,
    ) -> ServeRequest:
        """Tokenize and admit one request; returns its ``ServeRequest``
        (``.result(timeout)`` blocks for the translation). Raises
        ``Backpressure`` at capacity and ``ValueError`` for inputs no
        bucket can hold — both *before* the request costs decode work.

        Distributed tracing: a context already active on the calling
        thread (a replica handling a routed request) is adopted; a bare
        local submit mints its own, so standalone engines trace too.
        ``tier`` tags the request's SLO class for the burn-rate gauges.
        """
        if self._worker is None:
            raise RuntimeError("engine not started (use start() or `with`) ")
        ids = self.translator.src_pipe.ragged([text])[0]
        if len(ids) > self.boundaries[-1]:
            raise ValueError(
                f"input tokenizes to {len(ids)} ids, beyond the largest "
                f"bucket boundary {self.boundaries[-1]}; raise boundaries "
                "or shorten the input"
            )
        # Count the attempt BEFORE the queue decides: the conservation law
        # (metrics.check_conservation) needs every admission attempt in
        # ``submitted`` so rejected ones balance against ``rejected``.
        self.metrics.on_submit()
        ctx = _tracectx.current() or _tracectx.mint()
        with _tracectx.use(ctx), telemetry.span("serving.submit"):
            try:
                req = self.queue.submit(
                    text, ids, deadline_s=deadline_s, tier=tier
                )
            except Exception:
                self.metrics.on_reject()
                raise
        return req

    # -- the decode loop -----------------------------------------------------
    def _serve_loop(self) -> None:
        """Supervisor: keep a decode loop alive until ``stop()``.

        Two containment rings (docs/FAULT_TOLERANCE.md). Inner: a batch
        that raises is quarantined — its own requests fail with
        ``InternalError``, everything else keeps flowing. Outer: if the
        loop itself dies (batcher bug, quarantine path raising), it is
        restarted here rather than leaving a silently dead engine whose
        submitters all block until their deadlines; ``loop_restarts``
        counts how often that safety net caught something.
        """
        while not self._stop.is_set():
            try:
                self._decode_loop()
            except Exception:  # noqa: BLE001 — a dead loop, not a dead engine
                if self._stop.is_set():
                    break
                log.exception("decode loop died; restarting")
                self.metrics.on_loop_restart()

    def _decode_loop(self) -> None:
        if self.kv_mode == "paged":
            self._paged_loop()
            return
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.05)
            if batch is None:
                continue
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — a batch must never kill the loop
                self._quarantine(batch, e)

    # -- the paged decode loop ----------------------------------------------
    def _paged_loop(self) -> None:
        """Continuous paged serving: admit FIFO requests into free cache
        rows (chunk-budgeted prefill), launch ``steps_per_launch`` ragged
        decode steps over every occupied row, retire rows as they finish.
        A raised launch or admission quarantines the active set only —
        same inner containment ring as the padded loop."""
        while not self._stop.is_set():
            try:
                self.queue.expire_overdue()
                idle = not self.runtime.any_active()
                self._paged_admit(timeout=0.05 if idle else 0.0)
                if self._stop.is_set():
                    break
                if not self.runtime.any_active():
                    continue
                self._paged_step()
            except Exception as e:  # noqa: BLE001 — contain, keep serving
                self._paged_quarantine(e)
        self._paged_fail_active(EngineStopped("serving engine stopped"))

    def _admission_cost(self, req) -> int:
        """Prefill tokens admitting ``req`` will actually compute: zero
        for a prefix-cache hit (pages attach, no program runs), the
        chunk-padded prompt width otherwise. Racy against eviction — a
        stale zero only means one admission cycle briefly exceeds the
        budget, which the budget's own FIFO-prefix rule already permits
        for the head request."""
        if self.runtime.prefix_cache.contains(tuple(req.ids)):
            return 0
        return self.paged_batcher.cost(req.ids)

    def _paged_admit(self, timeout: float = 0.0) -> None:
        """Move pending requests onto free rows, bounded by the prefill
        token budget (chunked-prefill pacing). On page-pool pressure the
        untaken tail goes back to the queue head — transient, not an
        error."""
        taken = self.paged_batcher.take(
            max_requests=self.pool.free,
            token_budget=self.prefill_budget,
            timeout=timeout,
            cost_fn=self._admission_cost,
        )
        if not taken:
            # Empty admit round: no arrival will run the submit-side
            # sweep, so burn deadlines down directly — a queued request
            # must expire on time even on a quiet engine.
            self.queue.expire_now()
            return
        for i, req in enumerate(taken):
            if self._stop.is_set():
                self.queue.requeue_front(taken[i:])
                return
            row = self.pool.try_acquire(req.id)
            if row is None:  # unreachable: take() is bounded by free rows
                self.queue.requeue_front(taken[i:])
                return
            res = self.runtime.admit(req, row)
            if res is None:
                # Page pool full even after cache eviction: give the row
                # back and retry once in-flight rows free pages.
                self.pool.release_owner(req.id)
                self.queue.requeue_front(taken[i:])
                return
            kind, computed, real = res
            req.admit_time = self.clock()
            req.trace.mark(
                "admit", req.admit_time,
                kind=kind, prefill_tokens=computed, row=row,
            )
            self.metrics.on_token_slots(
                real=0 if kind == "hit" else real, padded=computed
            )

    def _paged_step(self) -> None:
        """One fault-injection point, one page-growth pass, one compiled
        launch, then host-side retirement of every finished row."""
        seq = self._batch_seq
        self._batch_seq += 1
        maybe_fault("decode_batch", batch=seq)
        for row in self.runtime.grow():
            req = self.runtime.retire(row)
            self.pool.release_owner(req.id)
            if not req.future.done():
                req.trace.mark("failed", self.clock(), reason="pages_exhausted")
                req.future.set_exception(InternalError(
                    "kv page pool exhausted mid-decode; size num_pages "
                    "for the worst case (the default does)"
                ))
                self.metrics.on_failure(1)
                self.metrics.on_trace(req)
        # Deadline sweep between launches: a row whose deadline passed
        # (or was force-expired by /v1/cancel) retires NOW, freeing its
        # pages and launch slot instead of decoding tokens no one will
        # read. Same retire path as completion, outcome ``expired`` — the
        # conservation ledger closes either way.
        now = self.clock()
        n_reaped = 0
        for row, req in self.runtime.active_rows():
            if not req.expired(now):
                continue
            self.runtime.retire(row)
            self.pool.release_owner(req.id)
            n_reaped += 1
            if not req.future.done():
                req.trace.mark("expire", now, reason="in_flight")
                req.future.set_exception(DeadlineExceeded(
                    f"request {req.id} expired mid-decode after "
                    f"{now - req.submit_time:.3f}s"
                ))
                self.metrics.on_expire(1, in_flight=True)
                self.metrics.on_slo(req.tier, True)
                self.metrics.on_trace(req)
        if n_reaped:
            telemetry.annotate(
                "serving.expire_in_flight", mode="paged", count=n_reaped
            )
        active = self.runtime.active_requests()
        n_active = len(active)
        if n_active == 0:
            return
        t0 = self.clock()
        with telemetry.span(
            "serving.batch", mode="paged", rows=n_active,
            steps=self.runtime.steps_per_launch,
            requests=[r.trace.trace_id for r in active],
        ), annotate("serve_decode_paged"):
            result = self.runtime.launch()
        decode_done = self.clock()
        decode_s = decode_done - t0
        for req in active:
            req.trace.note_launch()
        for req in result.first_emits:
            req.decode_done_time = decode_done
            req.trace.mark("first_token", decode_done)
        vocab = self.translator.trg_pipe.vocab
        n_completed = 0
        for req, ids, row, saw_eos in result.completed:
            self.runtime.retire(row)
            self.pool.release_owner(req.id)
            req.trace.mark("complete", decode_done, tokens=len(ids))
            req.future.set_result(" ".join(vocab.lookup_tokens(ids)))
            n_completed += 1
            now = self.clock()
            self.metrics.on_complete(
                queue_wait=(req.admit_time or req.submit_time)
                - req.submit_time,
                ttft=(req.decode_done_time or now) - req.submit_time,
                total=now - req.submit_time,
            )
            self.metrics.on_trace(req)
            self.metrics.on_slo(
                req.tier, req.deadline is not None and now > req.deadline
            )
        # Token ledger parity with the padded path (len(content)+1 per
        # request): real emits count EOS when emitted; a budget-exhausted
        # row gets its implicit stop token here.
        new_tokens = result.real_tokens + sum(
            1 for *_ , saw_eos in result.completed if not saw_eos
        )
        self.metrics.on_token_slots(
            real=result.real_tokens, padded=result.computed_slots
        )
        if n_completed:
            self.queue.note_serviced(n_completed, decode_s)
        self.metrics.on_batch(
            n_requests=n_active,
            max_batch=self.max_active,
            decode_s=decode_s,
            new_tokens=new_tokens,
            queue_depth=self.queue.depth,
            slot_occupancy=self.runtime.mem_pool.occupancy,
        )
        # A launch completed without raising: the degraded window (if
        # any) is over — /healthz flips back to ok.
        self._health.note_ok_batch(decode_done)

    def _paged_quarantine(self, exc: Exception) -> None:
        """Contain a failed launch/admission: the page store's contents
        are suspect, so every active request fails with ``InternalError``
        and the store resets (same shapes — zero recompiles); everything
        still queued keeps flowing."""
        if self._stop.is_set():
            return
        self._health.note_quarantine(self.clock())
        active = self.runtime.reset()
        log.info("quarantining paged launch of %d: %r", len(active), exc)
        telemetry.annotate(
            "serving.quarantine", mode="paged", requests=len(active),
            error=type(exc).__name__,
        )
        n = 0
        traces = []
        for req in active:
            self.pool.release_owner(req.id)
            if not req.future.done():
                req.trace.mark(
                    "failed", self.clock(), reason="quarantine",
                    error=type(exc).__name__,
                )
                err = InternalError(
                    f"decode batch failed internally ({type(exc).__name__});"
                    " only the active paged rows are affected"
                )
                err.__cause__ = exc
                req.future.set_exception(err)
                n += 1
                traces.append(req.trace.to_dict())
                self.metrics.on_trace(req)
        self.metrics.on_quarantine(n)
        self.metrics.on_failure(n)
        # The flight dump carries each quarantined request's full trace
        # timeline — postmortems see where every victim's time went, not
        # just how many there were.
        telemetry.dump_flight(
            f"serving.quarantine:{type(exc).__name__}",
            extra={
                "mode": "paged", "requests_failed": n,
                "request_traces": traces,
            },
        )

    def _paged_fail_active(self, exc: Exception) -> None:
        """Engine stopping with rows mid-decode: fail them terminally so
        the admission ledger still balances."""
        n = 0
        for req in self.runtime.reset():
            self.pool.release_owner(req.id)
            if not req.future.done():
                req.trace.mark("failed", self.clock(), reason="engine_stop")
                req.future.set_exception(exc)
                n += 1
        if n:
            self.metrics.on_failure(n)
            log.info("engine stop failed %d in-flight paged rows", n)

    def _quarantine(self, batch: Batch, exc: Exception) -> None:
        """Contain one failed batch: free its KV slots, fail its (and only
        its) requests with ``InternalError``, and count it."""
        self._health.note_quarantine(self.clock())
        log.info("quarantining batch of %d: %r", len(batch.requests), exc)
        telemetry.annotate(
            "serving.quarantine",
            boundary=batch.boundary, requests=len(batch.requests),
            error=type(exc).__name__,
        )
        n = 0
        traces = []
        for r in batch.requests:
            self.pool.release_owner(r.id)
            if not r.future.done():
                r.trace.mark(
                    "failed", self.clock(), reason="quarantine",
                    error=type(exc).__name__,
                )
                err = InternalError(
                    f"decode batch failed internally ({type(exc).__name__}); "
                    "only this batch's requests are affected"
                )
                err.__cause__ = exc
                r.future.set_exception(err)
                n += 1
                traces.append(r.trace.to_dict())
                self.metrics.on_trace(r)
        self.metrics.on_quarantine(n)
        self.metrics.on_failure(n)
        # Flight recorder: the quarantined batch's decode span (errored),
        # the annotation above, and every victim's trace timeline.
        telemetry.dump_flight(
            f"serving.quarantine:{type(exc).__name__}",
            extra={
                "boundary": batch.boundary, "requests_failed": n,
                "request_traces": traces,
            },
        )

    def _take_slots(self, batch: Batch) -> list[ServeRequest]:
        """All-or-nothing slot acquisition for the batch's live members,
        shedding any member whose deadline passes while waiting."""
        members = list(batch.requests)
        while members and not self._stop.is_set():
            now = self.clock()
            live = [r for r in members if not r.expired(now)]
            for r in members:
                if r not in live:
                    self.metrics.on_expire()
                    self.metrics.on_slo(r.tier, True)
                    r.trace.mark("expire", now, where="slot_wait")
                    r.future.set_exception(
                        DeadlineExceeded(
                            f"request {r.id} expired awaiting a KV slot"
                        )
                    )
            members = live
            if not members:
                break
            if self.pool.acquire_many([r.id for r in members], timeout=0.05):
                return members
        n_failed = 0
        for r in members:  # engine stopping
            if not r.future.done():
                r.trace.mark("failed", self.clock(), reason="engine_stop")
                r.future.set_exception(EngineStopped("engine stopping"))
                n_failed += 1
        if n_failed:
            self.metrics.on_failure(n_failed)  # terminal — conservation
        return []

    def _run_batch(self, batch: Batch) -> None:
        with telemetry.span(
            "serving.batch", mode="padded", boundary=batch.boundary,
            size=len(batch.requests),
            requests=[r.trace.trace_id for r in batch.requests],
        ):
            self._run_batch_inner(batch)

    def _run_batch_inner(self, batch: Batch) -> None:
        members = self._take_slots(batch)
        if not members:
            return
        # After slot acquisition, before decode: an injected failure here
        # exercises the full quarantine path, slot release included.
        seq = self._batch_seq
        self._batch_seq += 1
        maybe_fault("decode_batch", batch=seq)
        batch_start = self.clock()
        for r in members:
            r.trace.mark(
                "admit", batch_start,
                kind="padded", prefill_tokens=batch.boundary,
            )
        src = np.full((self.max_batch, batch.boundary), self._pad_id, np.int32)
        for i, r in enumerate(members):
            row = r.ids[: batch.boundary]
            src[i, : len(row)] = row
        # Filler rows replicate row 0: real tokens keep every attention row
        # well-formed, and rows past len(members) are simply discarded.
        for i in range(len(members), self.max_batch):
            src[i] = src[0]
        with annotate(f"serve_decode_b{batch.boundary}"):
            out = np.asarray(
                jax.block_until_ready(
                    self._decoders[batch.boundary](self.translator.params, src)
                )
            )
        decode_done = self.clock()

        from machine_learning_apache_spark_tpu.train.metrics import (
            strip_special_ids,
        )

        rows = strip_special_ids(
            out[: len(members)],
            pad_id=self._pad_id, sos_id=SOS_ID, eos_id=EOS_ID,
        )
        vocab = self.translator.trg_pipe.vocab
        new_tokens = 0
        real_decode = 0
        for r, row in zip(members, rows):
            r.decode_done_time = decode_done
            r.trace.note_launch()
            r.trace.mark("first_token", decode_done)
            new_tokens += len(row) + 1  # emitted ids + the eos/stop token
            real_decode += min(len(row) + 1, self.max_new_tokens)
            text = " ".join(vocab.lookup_tokens(row))
            # Slot frees at EOS — the row is done generating either way
            # (eos emitted, or the max_new_tokens budget is exhausted).
            self.pool.release_owner(r.id)
            r.trace.mark("complete", decode_done, tokens=len(row))
            r.future.set_result(text)
            done = self.clock()
            self.metrics.on_complete(
                queue_wait=batch_start - r.submit_time,
                ttft=decode_done - r.submit_time,
                total=done - r.submit_time,
            )
            self.metrics.on_trace(r)
            self.metrics.on_slo(
                r.tier, r.deadline is not None and done > r.deadline
            )
        # Padding-waste ledger: the rectangle this batch computed (every
        # row, filler included, at full boundary/budget width) versus the
        # tokens that were real.
        self.metrics.on_token_slots(
            real=sum(min(len(r.ids), batch.boundary) for r in members)
            + real_decode,
            padded=self.max_batch * (batch.boundary + self.max_new_tokens),
        )
        decode_s = decode_done - batch_start
        self.queue.note_serviced(len(members), decode_s)
        self.metrics.on_batch(
            n_requests=len(members),
            max_batch=self.max_batch,
            decode_s=decode_s,
            new_tokens=new_tokens,
            queue_depth=self.queue.depth,
            slot_occupancy=self.pool.occupancy,
        )
        # Batch retired cleanly: end of any post-quarantine degraded window.
        self._health.note_ok_batch(decode_done)
