"""Serving engine — the decode loop behind ``Translator.serve()``.

Wiring: caller threads tokenize and ``submit()`` into the admission
queue; one background worker pulls shape-bucketed batches from the
``Batcher``, takes KV slots for every member, pads the batch to the
bucket's static ``[max_batch, boundary]`` shape, and runs the compiled
cached decoder for that bucket. The eager path stays thin — tokenize,
pad, dispatch — and everything hot is an already-compiled XLA program
(the veScale split: request plumbing in Python, math in SPMD programs).

Shape discipline is the whole game: one jitted callable per bucket
boundary, batch always padded to ``max_batch`` (filler rows replicate
row 0 — valid tokens, so no all-masked softmax — and are discarded), so
``warmup()`` precompiles the complete program set and steady state runs
with zero recompiles. ``recompiles_after_warmup`` watches the jit caches
(via ``utils.compilation_cache``-style discipline, counted per callable)
and is the demo/bench acceptance gate.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

import jax
import numpy as np

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.data.text import EOS_ID, SOS_ID
from machine_learning_apache_spark_tpu.serving.batcher import Batch, Batcher
from machine_learning_apache_spark_tpu.serving.kv_slots import KVSlotPool
from machine_learning_apache_spark_tpu.serving.metrics import ServingMetrics
from machine_learning_apache_spark_tpu.serving.queue import (
    DeadlineExceeded,
    RequestQueue,
    ServeRequest,
)
from machine_learning_apache_spark_tpu.utils.faults import maybe_fault
from machine_learning_apache_spark_tpu.utils.logging import get_logger
from machine_learning_apache_spark_tpu.utils.profiling import annotate

log = get_logger(__name__)


class EngineStopped(RuntimeError):
    """The engine shut down before this request completed."""


class InternalError(RuntimeError):
    """The engine failed this request internally (its decode batch raised).

    The failure is *contained*: only the quarantined batch's requests see
    this, the decode loop keeps serving, and — because the per-bucket
    programs were compiled at warmup — recovery triggers zero recompiles.
    The original exception rides along as ``__cause__``.
    """


class ServingEngine:
    """Continuous-batching server over a ``Translator``-shaped bundle
    (``model``, ``params``, ``src_pipe``, ``trg_pipe``).

    >>> with translator.serve(max_batch=8, boundaries=(16, 32)) as eng:
    ...     futs = [eng.submit(s) for s in texts]
    ...     outs = [f.result(timeout=30) for f in futs]

    Tuning knobs (see docs/SERVING.md): ``boundaries`` pick the padded
    shapes (and so the compile set), ``max_batch`` the throughput/memory
    trade, ``max_wait_s`` the co-batching patience (tail latency bound),
    ``max_queue_depth`` the backpressure point, ``num_slots`` the
    in-flight ceiling.
    """

    def __init__(
        self,
        translator,
        *,
        boundaries: Sequence[int] = (16, 32, 64),
        max_batch: int = 8,
        max_wait_s: float = 0.02,
        max_queue_depth: int = 64,
        num_slots: int | None = None,
        max_new_tokens: int | None = None,
        default_deadline_s: float | None = None,
        method: str = "greedy",
        beam_size: int = 4,
        length_penalty: float = 0.6,
        clock=time.monotonic,
    ):
        cfg = translator.model.cfg
        boundaries = tuple(sorted(boundaries))
        if boundaries[-1] > cfg.max_len:
            raise ValueError(
                f"largest boundary {boundaries[-1]} exceeds the model's "
                f"max_len {cfg.max_len}; positions past max_len have no "
                "encoding"
            )
        if method not in ("greedy", "beam"):
            raise ValueError(
                f"method must be 'greedy' or 'beam', got {method!r}"
            )
        self.translator = translator
        self.boundaries = boundaries
        self.max_batch = max_batch
        self.max_new_tokens = (
            cfg.max_len - 1 if max_new_tokens is None else max_new_tokens
        )
        self.method = method
        self.clock = clock
        self.metrics = ServingMetrics(clock=clock)
        self.queue = RequestQueue(
            max_queue_depth, default_deadline_s=default_deadline_s,
            clock=clock, on_expire=self.metrics.on_expire,
        )
        self.batcher = Batcher(
            self.queue,
            boundaries=boundaries,
            max_batch=max_batch,
            max_wait_s=max_wait_s,
        )
        # 2× max_batch by default: one batch decoding plus one forming.
        self.pool = KVSlotPool(num_slots or 2 * max_batch)
        self._decoders = {
            b: self._make_decoder(beam_size, length_penalty)
            for b in boundaries
        }
        self._compiles_at_warmup: int | None = None
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        # Monotonic sequence over dispatched batches — the ``decode_batch``
        # fault-injection coordinate (worker thread only; no lock needed).
        self._batch_seq = 0

    def _make_decoder(self, beam_size: int, length_penalty: float):
        """One jitted decode callable (its own jit cache → per-bucket
        compile counting stays exact)."""
        from machine_learning_apache_spark_tpu.models import (
            beam_translate,
            greedy_translate_cached,
        )

        model, mnt = self.translator.model, self.max_new_tokens
        if self.method == "beam":
            fn = lambda p, s: beam_translate(  # noqa: E731
                model, p, s, beam_size=beam_size,
                length_penalty=length_penalty, max_new_tokens=mnt,
                sos_id=SOS_ID, eos_id=EOS_ID,
            )
        else:
            fn = lambda p, s: greedy_translate_cached(  # noqa: E731
                model, p, s, max_new_tokens=mnt, sos_id=SOS_ID, eos_id=EOS_ID,
            )
        return jax.jit(fn)

    # -- lifecycle -----------------------------------------------------------
    def start(self, *, warmup: bool = True) -> "ServingEngine":
        if self._worker is not None:
            raise RuntimeError("engine already started")
        if warmup:
            self.warmup()
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._serve_loop, name="serving-engine", daemon=True
        )
        self._worker.start()
        return self

    def stop(self, *, timeout: float = 30.0) -> None:
        if self._worker is None:
            return
        self._stop.set()
        with self.queue.cond:
            self.queue.cond.notify_all()
        self._worker.join(timeout)
        self._worker = None
        n = self.queue.fail_all(EngineStopped("serving engine stopped"))
        if n:
            # Counted into ``failed`` so the conservation law balances
            # across shutdown: stop-drained requests are terminal too.
            self.metrics.on_failure(n)
            log.info("engine stop failed %d queued requests", n)

    def __enter__(self) -> "ServingEngine":
        if self._worker is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- warmup / compile accounting ----------------------------------------
    def warmup(self) -> int:
        """Precompile every bucket's program on dummy full-size batches so
        no live request ever pays a compile. Returns the program count."""
        params = self.translator.params
        row = [SOS_ID, EOS_ID]
        for b in self.boundaries:
            src = np.full((self.max_batch, b), self._pad_id, np.int32)
            src[:, : len(row)] = row
            with annotate(f"serve_warmup_b{b}"):
                np.asarray(jax.block_until_ready(self._decoders[b](params, src)))
        self._compiles_at_warmup = self.compile_count()
        log.info(
            "warmup compiled %d bucket programs (max_batch=%d, buckets=%s)",
            len(self.boundaries), self.max_batch, list(self.boundaries),
        )
        return len(self.boundaries)

    def compile_count(self) -> int | None:
        """Total compiled programs across the bucket decoders, read from
        each jitted callable's cache (None if the jax build doesn't
        expose the probe)."""
        from machine_learning_apache_spark_tpu.utils.compilation_cache import (
            jit_cache_size,
        )

        sizes = [jit_cache_size(d) for d in self._decoders.values()]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)

    @property
    def recompiles_after_warmup(self) -> int | None:
        """Programs compiled since ``warmup()`` — 0 in healthy steady
        state (the demo/bench acceptance gate)."""
        n = self.compile_count()
        if n is None or self._compiles_at_warmup is None:
            return None
        return n - self._compiles_at_warmup

    # -- request path --------------------------------------------------------
    @property
    def _pad_id(self) -> int:
        return self.translator.model.cfg.pad_id

    def submit(self, text: str, *, deadline_s: float | None = None) -> ServeRequest:
        """Tokenize and admit one request; returns its ``ServeRequest``
        (``.result(timeout)`` blocks for the translation). Raises
        ``Backpressure`` at capacity and ``ValueError`` for inputs no
        bucket can hold — both *before* the request costs decode work."""
        if self._worker is None:
            raise RuntimeError("engine not started (use start() or `with`) ")
        ids = self.translator.src_pipe.ragged([text])[0]
        if len(ids) > self.boundaries[-1]:
            raise ValueError(
                f"input tokenizes to {len(ids)} ids, beyond the largest "
                f"bucket boundary {self.boundaries[-1]}; raise boundaries "
                "or shorten the input"
            )
        # Count the attempt BEFORE the queue decides: the conservation law
        # (metrics.check_conservation) needs every admission attempt in
        # ``submitted`` so rejected ones balance against ``rejected``.
        self.metrics.on_submit()
        with telemetry.span("serving.submit"):
            try:
                req = self.queue.submit(text, ids, deadline_s=deadline_s)
            except Exception:
                self.metrics.on_reject()
                raise
        return req

    # -- the decode loop -----------------------------------------------------
    def _serve_loop(self) -> None:
        """Supervisor: keep a decode loop alive until ``stop()``.

        Two containment rings (docs/FAULT_TOLERANCE.md). Inner: a batch
        that raises is quarantined — its own requests fail with
        ``InternalError``, everything else keeps flowing. Outer: if the
        loop itself dies (batcher bug, quarantine path raising), it is
        restarted here rather than leaving a silently dead engine whose
        submitters all block until their deadlines; ``loop_restarts``
        counts how often that safety net caught something.
        """
        while not self._stop.is_set():
            try:
                self._decode_loop()
            except Exception:  # noqa: BLE001 — a dead loop, not a dead engine
                if self._stop.is_set():
                    break
                log.exception("decode loop died; restarting")
                self.metrics.on_loop_restart()

    def _decode_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.05)
            if batch is None:
                continue
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — a batch must never kill the loop
                self._quarantine(batch, e)

    def _quarantine(self, batch: Batch, exc: Exception) -> None:
        """Contain one failed batch: free its KV slots, fail its (and only
        its) requests with ``InternalError``, and count it."""
        log.info("quarantining batch of %d: %r", len(batch.requests), exc)
        telemetry.annotate(
            "serving.quarantine",
            boundary=batch.boundary, requests=len(batch.requests),
            error=type(exc).__name__,
        )
        n = 0
        for r in batch.requests:
            self.pool.release_owner(r.id)
            if not r.future.done():
                err = InternalError(
                    f"decode batch failed internally ({type(exc).__name__}); "
                    "only this batch's requests are affected"
                )
                err.__cause__ = exc
                r.future.set_exception(err)
                n += 1
        self.metrics.on_quarantine(n)
        self.metrics.on_failure(n)
        # Flight recorder: the quarantined batch's decode span (errored) and
        # the annotation above are the newest events in the dump.
        telemetry.dump_flight(
            f"serving.quarantine:{type(exc).__name__}",
            extra={"boundary": batch.boundary, "requests_failed": n},
        )

    def _take_slots(self, batch: Batch) -> list[ServeRequest]:
        """All-or-nothing slot acquisition for the batch's live members,
        shedding any member whose deadline passes while waiting."""
        members = list(batch.requests)
        while members and not self._stop.is_set():
            now = self.clock()
            live = [r for r in members if not r.expired(now)]
            for r in members:
                if r not in live:
                    self.metrics.on_expire()
                    r.future.set_exception(
                        DeadlineExceeded(
                            f"request {r.id} expired awaiting a KV slot"
                        )
                    )
            members = live
            if not members:
                break
            if self.pool.acquire_many([r.id for r in members], timeout=0.05):
                return members
        n_failed = 0
        for r in members:  # engine stopping
            if not r.future.done():
                r.future.set_exception(EngineStopped("engine stopping"))
                n_failed += 1
        if n_failed:
            self.metrics.on_failure(n_failed)  # terminal — conservation
        return []

    def _run_batch(self, batch: Batch) -> None:
        with telemetry.span(
            "serving.batch", boundary=batch.boundary, size=len(batch.requests)
        ):
            self._run_batch_inner(batch)

    def _run_batch_inner(self, batch: Batch) -> None:
        members = self._take_slots(batch)
        if not members:
            return
        # After slot acquisition, before decode: an injected failure here
        # exercises the full quarantine path, slot release included.
        seq = self._batch_seq
        self._batch_seq += 1
        maybe_fault("decode_batch", batch=seq)
        batch_start = self.clock()
        src = np.full((self.max_batch, batch.boundary), self._pad_id, np.int32)
        for i, r in enumerate(members):
            row = r.ids[: batch.boundary]
            src[i, : len(row)] = row
        # Filler rows replicate row 0: real tokens keep every attention row
        # well-formed, and rows past len(members) are simply discarded.
        for i in range(len(members), self.max_batch):
            src[i] = src[0]
        with annotate(f"serve_decode_b{batch.boundary}"):
            out = np.asarray(
                jax.block_until_ready(
                    self._decoders[batch.boundary](self.translator.params, src)
                )
            )
        decode_done = self.clock()

        from machine_learning_apache_spark_tpu.train.metrics import (
            strip_special_ids,
        )

        rows = strip_special_ids(
            out[: len(members)],
            pad_id=self._pad_id, sos_id=SOS_ID, eos_id=EOS_ID,
        )
        vocab = self.translator.trg_pipe.vocab
        new_tokens = 0
        for r, row in zip(members, rows):
            r.decode_done_time = decode_done
            new_tokens += len(row) + 1  # emitted ids + the eos/stop token
            text = " ".join(vocab.lookup_tokens(row))
            # Slot frees at EOS — the row is done generating either way
            # (eos emitted, or the max_new_tokens budget is exhausted).
            self.pool.release_owner(r.id)
            r.future.set_result(text)
            done = self.clock()
            self.metrics.on_complete(
                queue_wait=batch_start - r.submit_time,
                ttft=decode_done - r.submit_time,
                total=done - r.submit_time,
            )
        decode_s = decode_done - batch_start
        self.queue.note_serviced(len(members), decode_s)
        self.metrics.on_batch(
            n_requests=len(members),
            max_batch=self.max_batch,
            decode_s=decode_s,
            new_tokens=new_tokens,
            queue_depth=self.queue.depth,
            slot_occupancy=self.pool.occupancy,
        )
