"""Fixed-pool KV slot manager — bounded in-flight decode state.

Each in-flight request owns one slot of decode-cache capacity for the
duration of its batch (Ragged Paged Attention's slot discipline, at batch
granularity: the engine's KV caches are per-batch scan state, so a slot
here is the *right to occupy a cache row*, and the pool bound is the hard
ceiling on concurrently-decoding requests). Slots free on EOS — every
completed row — and on deadline expiry of a request that died holding
one; an exhausted pool makes the batcher's next batch wait instead of
oversubscribing device memory.

The pool is a condition-backed free list with owner tracking, so a crash
path can free by request id without knowing which slot it held, plus the
occupancy/high-water counters the metrics ledger reports.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque


class KVSlotPool:
    def __init__(self, num_slots: int, *, clock=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self._cond = threading.Condition()
        self._free = list(range(num_slots - 1, -1, -1))  # stack, slot 0 first
        self._owner_of_slot: dict[int, int] = {}
        self._slots_of_owner: dict[int, list[int]] = {}
        # FIFO ticket queue for blocking acquirers: a waiter may only take
        # capacity when its ticket is at the head, so a large all-or-nothing
        # batch cannot be starved by a stream of small batches arriving
        # later and skimming slots as they free.
        self._tickets: deque[int] = deque()
        self._next_ticket = itertools.count()
        self.total_acquired = 0
        self.total_released = 0
        self.high_water = 0

    # -- acquisition ---------------------------------------------------------
    def try_acquire(self, owner_id: int) -> int | None:
        """One slot for ``owner_id``, or None if the pool is dry. Yields
        to queued blocking acquirers — a non-blocking grab must not skim
        a slot an earlier ``acquire_many`` is waiting on."""
        with self._cond:
            if not self._free or self._tickets:
                return None
            return self._take_locked(owner_id)

    def acquire_many(
        self, owner_ids: list[int], timeout: float | None = None
    ) -> list[int] | None:
        """Slots for a whole batch, all-or-nothing; blocks up to
        ``timeout`` for enough capacity. All-or-nothing keeps a formed
        batch indivisible — partial grants would strand requests that the
        batcher already removed from the queue. Grants are FIFO in arrival
        order: a waiter only takes slots once every earlier waiter has
        been served, so a full-pool batch eventually drains instead of
        being starved by smaller batches slipping in behind it."""
        if len(owner_ids) > self.num_slots:
            raise ValueError(
                f"batch of {len(owner_ids)} can never fit a pool of "
                f"{self.num_slots} slots"
            )
        ticket = next(self._next_ticket)
        with self._cond:
            self._tickets.append(ticket)
            try:
                ok = self._cond.wait_for(
                    lambda: (
                        self._tickets[0] == ticket
                        and len(self._free) >= len(owner_ids)
                    ),
                    timeout,
                )
                if not ok:
                    return None
                return [self._take_locked(o) for o in owner_ids]
            finally:
                self._tickets.remove(ticket)
                self._cond.notify_all()

    def _take_locked(self, owner_id: int) -> int:
        slot = self._free.pop()
        self._owner_of_slot[slot] = owner_id
        self._slots_of_owner.setdefault(owner_id, []).append(slot)
        self.total_acquired += 1
        self.high_water = max(self.high_water, self.in_use)
        return slot

    # -- release -------------------------------------------------------------
    def release(self, slot: int) -> None:
        with self._cond:
            owner = self._owner_of_slot.pop(slot, None)
            if owner is None:
                raise ValueError(f"slot {slot} is not held")
            owned = self._slots_of_owner.get(owner, [])
            if slot in owned:
                owned.remove(slot)
                if not owned:
                    del self._slots_of_owner[owner]
            self._free.append(slot)
            self.total_released += 1
            self._cond.notify_all()

    def release_owner(self, owner_id: int) -> int:
        """Free every slot held by ``owner_id`` (EOS or deadline death);
        returns how many were freed. Idempotent — a request that never
        got a slot frees zero."""
        with self._cond:
            owned = self._slots_of_owner.pop(owner_id, [])
            for slot in owned:
                del self._owner_of_slot[slot]
                self._free.append(slot)
            self.total_released += len(owned)
            if owned:
                self._cond.notify_all()
            return len(owned)

    # -- introspection -------------------------------------------------------
    @property
    def in_use(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        """In-use fraction of the pool, 0.0–1.0."""
        return self.in_use / self.num_slots

    def holder(self, slot: int) -> int | None:
        with self._cond:
            return self._owner_of_slot.get(slot)
