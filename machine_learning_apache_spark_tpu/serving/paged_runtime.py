"""Paged decode runtime — the device half of the ragged serving engine.

Where the padded engine compiles one decoder program per bucket and pays
``max_batch x boundary`` prefill plus ``max_batch x max_new_tokens``
decode slots for every batch, this runtime keeps **two page stores** on
device, each ``[layers, 2, num_pages, page_size, d_model]``:

- the **self store** holds generated-token K/V. It is small (worst case
  ``max_active x ceil(max_new_tokens/page_size)`` pages) because it is
  the launch program's scan carry — every decode step scatters into it,
  and on backends without buffer donation (CPU) a carry is copied per
  step, so its size is the per-step overhead.
- the **mem store** holds prompt cross-attention K/V and the prefix
  cache. It is written by prefill and **read-only during decode**, so
  the launch program never carries or copies it — the cache can grow
  large without taxing the decode loop.

With ``kv_dtype="int8"`` the mem store's payload is int8 with per-page
absmax scales (EQuARX-style, arxiv 2506.17615) kept in a parallel
``[layers, 2, num_pages, page_size]`` float32 plane addressed by the
same block tables — 4 fp32 bytes shrink to 1 int8 byte + 4/d_model
scale bytes per element, so the same HBM budget holds ~4x the pages
and the pages-limited max-concurrency ceiling rises with it. The
attention kernel dequantizes per gathered page before its dots
(``ops.attention.ragged_paged_attention``); scales travel with their
pages through PrefixCache hits/evictions because they live at the same
page index. The SELF store can follow via ``quantize_self=True``
(per-slot scales, written by the decode scatter).

Exactly two kinds of compiled program run over them:

- **prefill** (one per chunk count): encode a prompt padded to the next
  ``prefill_chunk`` multiple, project every decoder layer's cross-attn
  K/V (``Transformer.prefill_paged``), and scatter them into the
  request's memory pages. Short prompts pay a short program — chunk
  padding, not bucket padding — and a ``PrefixCache`` hit skips the
  program entirely.
- **launch** (exactly one): ``steps_per_launch`` greedy decode steps via
  ``lax.scan`` over ``Transformer.decode_step_paged``, serving every
  occupied row regardless of its prompt length or generation depth —
  block tables and per-row lengths make raggedness a *data* property, so
  any batch occupancy/length mix reuses the same XLA program and the
  zero-recompile invariant holds across arbitrary traffic.

Host state (block tables, cursors, row<->request maps) is plain numpy,
mutated only by the engine's decode thread; the device stores are jax
arrays threaded through the jitted programs (donated off-CPU). Page
accounting delegates to one ``KVPagePool`` per store: rows allocate
their first self page at admission and **grow one page at a time** as
the cursor crosses page boundaries, free everything on EOS/expiry via
the request id, and share refcounted prefix pages (mem pool) through
the cache.

Decode discipline (kept bit-consistent with the padded scan): each step
scatters the new K/V at the row's *old* cursor, emits
``argmax`` (pad forced for finished rows), then advances the cursor for
unfinished rows only. A row finishes on emitting EOS, on exhausting the
``max_new_tokens`` budget, or on emitting pad (the padded path can decode
*through* an emitted pad because its dense mask hides interior holes;
length-addressed block tables cannot represent a hole, so the paged path
treats an emitted pad as terminal — in practice an untrained-corner
behaviour that greedy decoding does not produce).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_apache_spark_tpu.serving.kv_pages import (
    NULL_PAGE,
    KVPagePool,
    PrefixCache,
)
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@dataclasses.dataclass
class LaunchResult:
    """What one launch produced, for the engine's bookkeeping."""

    #: rows that finished this launch: (request, content token ids —
    #: sos/eos/pad excluded, row index, whether EOS was actually emitted)
    completed: list
    #: requests whose FIRST token arrived this launch (TTFT stamp)
    first_emits: list
    #: real tokens emitted this launch (EOS included; pads excluded)
    real_tokens: int
    #: decode-step slots the program computed (max_active x steps)
    computed_slots: int
    steps: int
    n_active: int


class PagedDecodeRuntime:
    """Page store + compiled programs + per-row host state.

    Single-threaded by contract: every method is called from the
    engine's decode thread (the pools it owns are internally locked, so
    introspection from other threads stays safe).
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_active: int,
        max_src: int,
        max_new_tokens: int,
        page_size: int = 8,
        prefill_chunk: int = 8,
        steps_per_launch: int = 4,
        num_pages: int | None = None,
        prefix_cache_size: int = 32,
        kv_dtype: str = "float32",
        quantize_self: bool = False,
        sos_id: int,
        eos_id: int,
        pad_id: int,
    ):
        cfg = model.cfg
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if prefill_chunk % page_size != 0:
            raise ValueError(
                f"prefill_chunk ({prefill_chunk}) must be a multiple of "
                f"page_size ({page_size}) so memory pages fill exactly"
            )
        if max_new_tokens > cfg.max_len:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds max_len "
                f"{cfg.max_len}: decode positions would have no encoding"
            )
        if kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r} (expected 'float32' or "
                "'int8')"
            )
        self.model = model
        self.params = params
        self.max_active = max_active
        self.max_new_tokens = max_new_tokens
        self.page_size = page_size
        self.prefill_chunk = prefill_chunk
        self.steps_per_launch = steps_per_launch
        self.sos_id, self.eos_id, self.pad_id = sos_id, eos_id, pad_id
        # Quantized memory plane (EQuARX-style per-page absmax int8,
        # arxiv 2506.17615): the MEM store quantizes first — it dominates
        # footprint (prompt cross-KV + prefix-cache residents) and is
        # read-only during decode, so it quantizes once at prefill. The
        # small SELF scan-carry store follows only on request
        # (``quantize_self``): its per-step scatter must also write
        # per-slot scales, and the capacity win is marginal.
        self.kv_dtype = kv_dtype
        self.quantize_self = bool(quantize_self)
        self._mem_quant = kv_dtype == "int8"
        self._self_quant = self._mem_quant and self.quantize_self

        # Geometry: self pages cover the max_new_tokens budget; memory
        # pages cover the largest chunk-padded prompt. The self store is
        # always sized at worst case (it is small, and the launch carry
        # must never starve mid-decode); ``num_pages`` bounds the MEM
        # store — the big one, holding prompts and the prefix cache.
        self.self_pages = -(-max_new_tokens // page_size)
        self.max_chunks = -(-max_src // prefill_chunk)
        self.mem_pages = self.max_chunks * prefill_chunk // page_size
        self.num_self_pages = 1 + max_active * self.self_pages
        if num_pages is None:
            # Worst case all rows full-length prompts, plus the prefix
            # cache at capacity, plus the reserved null page.
            num_pages = (
                1 + (max_active + prefix_cache_size) * self.mem_pages
            )
        elif num_pages < 1 + self.mem_pages:
            raise ValueError(
                f"num_pages={num_pages} cannot hold even one full prompt "
                f"({self.mem_pages} pages + the reserved null page)"
            )
        self.num_pages = num_pages

        self._self_shape = (
            cfg.num_layers, 2, self.num_self_pages, page_size, cfg.d_model
        )
        self._mem_shape = (
            cfg.num_layers, 2, num_pages, page_size, cfg.d_model
        )
        self._self_store_dtype = (
            jnp.int8 if self._self_quant else cfg.dtype
        )
        self._mem_store_dtype = jnp.int8 if self._mem_quant else cfg.dtype
        # Per-slot dequantization scales, same block-table addressing as
        # the payload: slot (p, s) dequantizes as pages[p, s] * scale[p, s].
        # MEM scales are per *page* (one absmax per page, broadcast over
        # its slots); SELF scales are per slot (each decode step scatters
        # one position, so rescaling the whole page would corrupt the
        # int8 already written).
        self._self_scale_shape = (
            cfg.num_layers, 2, self.num_self_pages, page_size
        )
        self._mem_scale_shape = (cfg.num_layers, 2, num_pages, page_size)
        self.kv_self = jnp.zeros(self._self_shape, self._self_store_dtype)
        self.kv_mem = jnp.zeros(self._mem_shape, self._mem_store_dtype)
        self.self_scale = (
            jnp.zeros(self._self_scale_shape, jnp.float32)
            if self._self_quant else None
        )
        self.mem_scale = (
            jnp.zeros(self._mem_scale_shape, jnp.float32)
            if self._mem_quant else None
        )

        # Dtype-aware byte accounting: a page costs its payload plus (for
        # quantized stores) one fp32 scale per slot, across every layer's
        # k and v planes.
        d = cfg.d_model
        self.mem_page_bytes = cfg.num_layers * 2 * page_size * (
            d * np.dtype(self._mem_store_dtype).itemsize
            + (4 if self._mem_quant else 0)
        )
        self.self_page_bytes = cfg.num_layers * 2 * page_size * (
            d * np.dtype(self._self_store_dtype).itemsize
            + (4 if self._self_quant else 0)
        )
        self.self_pool = KVPagePool(
            self.num_self_pages, page_bytes=self.self_page_bytes
        )
        self.mem_pool = KVPagePool(
            num_pages, page_bytes=self.mem_page_bytes
        )
        self.prefix_cache = PrefixCache(self.mem_pool, prefix_cache_size)
        self.prefix_cache_size = prefix_cache_size

        # Donation lets each program write the store in place; CPU jax
        # does not implement it, so gate to keep the logs clean there.
        self._donate = jax.default_backend() != "cpu"
        self._prefill_fns = {
            c: self._make_prefill(c) for c in range(1, self.max_chunks + 1)
        }
        self._launch_fn = self._make_launch()

        self._reset_host_state()

    def _zero_stores(self) -> None:
        """Fresh zero payload + scale arrays — identical shapes/dtypes to
        the live ones, so compiled programs stay valid."""
        self.kv_self = jnp.zeros(self._self_shape, self._self_store_dtype)
        self.kv_mem = jnp.zeros(self._mem_shape, self._mem_store_dtype)
        if self._self_quant:
            self.self_scale = jnp.zeros(
                self._self_scale_shape, jnp.float32
            )
        if self._mem_quant:
            self.mem_scale = jnp.zeros(self._mem_scale_shape, jnp.float32)

    def _reset_host_state(self) -> None:
        R, Ps, Pm = self.max_active, self.self_pages, self.mem_pages
        self._self_tbl = np.full((R, Ps), NULL_PAGE, np.int32)
        self._mem_tbl = np.full((R, Pm), NULL_PAGE, np.int32)
        self._mem_len = np.zeros(R, np.int32)
        self._cursor = np.zeros(R, np.int32)
        self._token = np.full(R, self.pad_id, np.int32)
        self._finished = np.ones(R, bool)
        self._self_alloc = np.zeros(R, np.int32)  # self pages held per row
        self._req_of_row = [None] * R
        self._emitted: list[list[int]] = [[] for _ in range(R)]
        self._awaiting_first = np.zeros(R, bool)

    # -- compiled programs ---------------------------------------------------
    def _make_prefill(self, chunks: int):
        model = self.model
        layers = model.cfg.num_layers
        width = chunks * self.prefill_chunk
        n_pages = width // self.page_size
        page, d = self.page_size, model.cfg.d_model
        mem_quant = self._mem_quant

        def project(params, src):
            _, var = model.apply(
                {"params": params}, src,
                method="prefill_paged", mutable=["paged"],
            )
            sown = var["paged"]["decoder"]
            k = jnp.stack([
                sown[f"layer_{i}"]["cross_attn"]["k_mem"][0][0]
                for i in range(layers)
            ])
            v = jnp.stack([
                sown[f"layer_{i}"]["cross_attn"]["v_mem"][0][0]
                for i in range(layers)
            ])
            kv = jnp.stack([k, v], axis=1)  # [L, 2, width, d]
            return kv.reshape(layers, 2, n_pages, page, d)

        if not mem_quant:
            def fn(params, kv_mem, src, mem_table):
                kv = project(params, src)
                return kv_mem.at[:, :, mem_table].set(
                    kv.astype(kv_mem.dtype)
                )

            donate = (1,) if self._donate else ()
            return jax.jit(fn, donate_argnums=donate)

        def fn(params, kv_mem, mem_scale, src, mem_table):
            kv = project(params, src)
            # Per-page absmax quantization (the zero1 comms scheme, minus
            # the N-way-sum headroom — pages are never summed): one scale
            # per (layer, k/v, page), broadcast to the page's slots so
            # the kernel's per-slot dequant addressing stays uniform
            # between the MEM and SELF stores.
            absmax = jnp.max(jnp.abs(kv), axis=(3, 4))  # [L, 2, n_pages]
            s = jnp.maximum(absmax / 127.0, jnp.float32(1e-30))
            q = jnp.clip(
                jnp.round(kv / s[..., None, None]), -127, 127
            ).astype(jnp.int8)
            kv_mem = kv_mem.at[:, :, mem_table].set(q)
            slot_s = jnp.broadcast_to(
                s[..., None], (layers, 2, n_pages, page)
            )
            mem_scale = mem_scale.at[:, :, mem_table].set(slot_s)
            return kv_mem, mem_scale

        donate = (1, 2) if self._donate else ()
        return jax.jit(fn, donate_argnums=donate)

    def _make_launch(self):
        model = self.model
        layers = model.cfg.num_layers
        page, Ps = self.page_size, self.self_pages
        T, mnt = self.steps_per_launch, self.max_new_tokens
        eos, pad = self.eos_id, self.pad_id
        self_quant = self._self_quant

        def fn(params, kv_self, kv_mem, token, cursor, finished,
               self_tbl, mem_tbl, mem_len, self_scale, mem_scale):
            # Only the self store (and, when self-quantized, its scale
            # plane) rides the scan carry: the mem store and its scales
            # are read-only during decode, so they enter as closed-over
            # operands and are never copied per step. For fp32 stores the
            # scale arguments are None — an empty pytree, so the compiled
            # program is unchanged from the unquantized build.
            def step(carry, _):
                kv_self, self_scale, token, cursor, finished = carry
                logits, var = model.apply(
                    {"params": params}, token[:, None], kv_self, kv_mem,
                    self_tbl, cursor, mem_tbl, mem_len, cursor[:, None],
                    self_scale, mem_scale,
                    method="decode_step_paged", mutable=["paged"],
                )
                sown = var["paged"]["decoder"]
                k = jnp.stack([
                    sown[f"layer_{i}"]["self_attn"]["k_new"][0]
                    for i in range(layers)
                ])
                v = jnp.stack([
                    sown[f"layer_{i}"]["self_attn"]["v_new"][0]
                    for i in range(layers)
                ])
                knv = jnp.stack([k, v], axis=1)  # [L, 2, R, d]
                # Scatter at the old cursor; frozen rows write the null
                # page (harmless by reservation).
                pidx = jnp.minimum(cursor // page, Ps - 1)
                pids = jnp.take_along_axis(
                    self_tbl, pidx[:, None], axis=1
                )[:, 0]
                pids = jnp.where(finished, NULL_PAGE, pids)
                offs = cursor % page
                if self_quant:
                    # Per-slot quantization: this step writes exactly one
                    # slot per row, so its scale lands next to it — the
                    # int8 already on the page keeps its own scales.
                    absmax = jnp.max(jnp.abs(knv), axis=-1)  # [L, 2, R]
                    s = jnp.maximum(
                        absmax / 127.0, jnp.float32(1e-30)
                    )
                    q = jnp.clip(
                        jnp.round(knv / s[..., None]), -127, 127
                    )
                    kv_self = kv_self.at[:, :, pids, offs, :].set(
                        q.astype(kv_self.dtype)
                    )
                    self_scale = self_scale.at[:, :, pids, offs].set(s)
                else:
                    kv_self = kv_self.at[:, :, pids, offs, :].set(
                        knv.astype(kv_self.dtype)
                    )
                emit = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
                emit = jnp.where(finished, pad, emit)
                cursor = cursor + jnp.where(finished, 0, 1).astype(jnp.int32)
                finished = (
                    finished
                    | (emit == eos)
                    | (emit == pad)
                    | (cursor >= mnt)
                )
                return (kv_self, self_scale, emit, cursor, finished), emit

            carry, emits = jax.lax.scan(
                step,
                (kv_self, self_scale, token, cursor, finished),
                None,
                length=T,
            )
            kv_self, self_scale, token, cursor, finished = carry
            return kv_self, self_scale, token, cursor, finished, emits

        donate = ((1, 9) if self_quant else (1,)) if self._donate else ()
        return jax.jit(fn, donate_argnums=donate)

    def jit_fns(self) -> list:
        """Every jitted program, for the engine's compile counting."""
        return [*self._prefill_fns.values(), self._launch_fn]

    def warmup(self) -> int:
        """Compile every prefill width and the launch program against the
        live stores (null-page targets, no rows active) so steady state
        never pays a trace. Returns the program count."""
        seed = np.array([self.sos_id, self.eos_id], np.int32)
        for c, fn in self._prefill_fns.items():
            width = c * self.prefill_chunk
            src = np.full((1, width), self.pad_id, np.int32)
            src[0, : len(seed)] = seed
            tbl = np.full(width // self.page_size, NULL_PAGE, np.int32)
            if self._mem_quant:
                self.kv_mem, self.mem_scale = fn(
                    self.params, self.kv_mem, self.mem_scale, src, tbl
                )
            else:
                self.kv_mem = fn(self.params, self.kv_mem, src, tbl)
        out = self._launch_fn(
            self.params, self.kv_self, self.kv_mem, self._token,
            self._cursor, self._finished, self._self_tbl, self._mem_tbl,
            self._mem_len, self.self_scale, self.mem_scale,
        )
        self.kv_self = out[0]
        if self._self_quant:
            self.self_scale = out[1]
        jax.block_until_ready(self.kv_self)
        # Warmup scribbled on the null pages; reset the stores for
        # hygiene (same shapes and dtypes, so no recompile).
        self._zero_stores()
        return len(self._prefill_fns) + 1

    # -- admission -----------------------------------------------------------
    def _acquire_mem_pages(self, n: int, owner) -> list[int] | None:
        pages = self.mem_pool.try_acquire(n, owner)
        if pages is None:
            # Pressure valve: cached prefixes are a luxury, live requests
            # are not.
            self.prefix_cache.evict_until_free(n)
            pages = self.mem_pool.try_acquire(n, owner)
        return pages

    def admit(self, req, row: int):
        """Place ``req`` on ``row``: attach (cache hit) or prefill (miss)
        its memory pages, allocate its first self page, and arm the row
        for decode. Returns ``(kind, padded_width, real_len)`` with kind
        in {"hit", "miss"} — a hit computes nothing, so its width is 0 —
        or None if the page pool cannot hold the request right now (the
        caller requeues; no references are leaked, and a miss's finished
        prefill survives in the cache for the retry)."""
        ids = list(req.ids)
        key = tuple(ids)
        width = _round_up(max(len(ids), 1), self.prefill_chunk)
        n_mem = width // self.page_size
        entry = self.prefix_cache.get(key, owner=req.id)
        if entry is None:
            pages = self._acquire_mem_pages(n_mem, req.id)
            if pages is None:
                return None
            src = np.full((1, width), self.pad_id, np.int32)
            src[0, : len(ids)] = ids
            fn = self._prefill_fns[width // self.prefill_chunk]
            if self._mem_quant:
                self.kv_mem, self.mem_scale = fn(
                    self.params, self.kv_mem, self.mem_scale, src,
                    np.asarray(pages, np.int32),
                )
            else:
                self.kv_mem = fn(
                    self.params, self.kv_mem, src,
                    np.asarray(pages, np.int32),
                )
            self.prefix_cache.put(key, pages, n_pages=n_mem,
                                  src_len=len(ids))
            kind, computed = "miss", width
        else:
            pages = entry["pages"]
            kind, computed = "hit", 0
        first = self.self_pool.try_acquire(1, req.id)
        if first is None:
            # Drop this request's references; a miss's pages stay alive
            # under the cache's own reference — the work is not lost.
            self.mem_pool.release_owner(req.id)
            return None
        self._req_of_row[row] = req
        self._emitted[row] = []
        self._awaiting_first[row] = True
        self._self_tbl[row, :] = NULL_PAGE
        self._self_tbl[row, 0] = first[0]
        self._self_alloc[row] = 1
        self._mem_tbl[row, :] = NULL_PAGE
        self._mem_tbl[row, : len(pages)] = pages
        self._mem_len[row] = len(ids)
        self._cursor[row] = 0
        self._token[row] = self.sos_id
        self._finished[row] = False
        return kind, computed, len(ids)

    def grow(self) -> list[int]:
        """Lazy self-page growth: before a launch, extend every active
        row's block table to cover the cursors the next
        ``steps_per_launch`` steps can reach. The self pool is sized at
        worst case, so starvation is impossible by construction; the
        starved-row return stays as the engine's defensive contract (it
        must fail such rows before launching, or their writes would land
        on the null page and corrupt reads of it)."""
        starved = []
        for r in range(self.max_active):
            req = self._req_of_row[r]
            if req is None or self._finished[r]:
                continue
            last = min(
                int(self._cursor[r]) + self.steps_per_launch - 1,
                self.max_new_tokens - 1,
            )
            need = last // self.page_size + 1
            have = int(self._self_alloc[r])
            if need <= have:
                continue
            got = self.self_pool.try_acquire(need - have, req.id)
            if got is None:
                starved.append(r)
                continue
            self._self_tbl[r, have:need] = got
            self._self_alloc[r] = need
        return starved

    # -- decode --------------------------------------------------------------
    def any_active(self) -> bool:
        return any(r is not None for r in self._req_of_row)

    def active_count(self) -> int:
        return sum(r is not None for r in self._req_of_row)

    def launch(self) -> LaunchResult:
        """Run one compiled multi-step decode over every row and fold the
        emitted tokens into per-row transcripts."""
        out = self._launch_fn(
            self.params, self.kv_self, self.kv_mem, self._token,
            self._cursor, self._finished, self._self_tbl, self._mem_tbl,
            self._mem_len, self.self_scale, self.mem_scale,
        )
        self.kv_self = out[0]
        if self._self_quant:
            self.self_scale = out[1]
        emits = np.asarray(jax.block_until_ready(out[5]))
        # np.array (copy): host state is mutated by admit/retire, and a
        # bare asarray view of a jax buffer is read-only.
        self._token = np.array(out[2])
        self._cursor = np.array(out[3])
        self._finished = np.array(out[4])
        completed, first_emits, real = [], [], 0
        for r in range(self.max_active):
            req = self._req_of_row[r]
            if req is None:
                continue
            saw_eos = False
            for e in emits[:, r]:
                e = int(e)
                if e == self.pad_id:
                    break
                if self._awaiting_first[r]:
                    self._awaiting_first[r] = False
                    first_emits.append(req)
                real += 1
                if e == self.eos_id:
                    saw_eos = True
                    break
                self._emitted[r].append(e)
            if self._finished[r]:
                completed.append((req, self._emitted[r], r, saw_eos))
        return LaunchResult(
            completed=completed,
            first_emits=first_emits,
            real_tokens=real,
            computed_slots=self.max_active * self.steps_per_launch,
            steps=self.steps_per_launch,
            n_active=self.active_count(),
        )

    # -- retirement / containment -------------------------------------------
    def retire(self, row: int):
        """Free a finished (or failed) row: drop every page reference the
        request holds — its self pages free now, shared prefix pages only
        once the cache and other holders let go. Returns the request."""
        req = self._req_of_row[row]
        if req is None:
            return None
        self._req_of_row[row] = None
        self._emitted[row] = []
        self._awaiting_first[row] = False
        self._finished[row] = True
        self._token[row] = self.pad_id
        self._cursor[row] = 0
        self._self_tbl[row, :] = NULL_PAGE
        self._mem_tbl[row, :] = NULL_PAGE
        self._mem_len[row] = 0
        self._self_alloc[row] = 0
        self.self_pool.release_owner(req.id)
        self.mem_pool.release_owner(req.id)
        return req

    def active_requests(self) -> list:
        return [r for r in self._req_of_row if r is not None]

    def active_rows(self) -> list:
        """``(row, request)`` pairs for every occupied row — the engine's
        between-launch deadline sweep walks this to :meth:`retire` expired
        rows without reaching into private row state."""
        return [
            (row, req)
            for row, req in enumerate(self._req_of_row)
            if req is not None
        ]

    def reset(self) -> list:
        """Quarantine path: the store's contents are suspect, so drop
        everything — returns the requests that were active (the caller
        fails them). Fresh zero store keeps the compiled programs valid
        (same shapes), so recovery costs zero recompiles."""
        active = self.active_requests()
        self.self_pool = KVPagePool(
            self.num_self_pages, page_bytes=self.self_page_bytes
        )
        self.mem_pool = KVPagePool(
            self.num_pages, page_bytes=self.mem_page_bytes
        )
        self.prefix_cache = PrefixCache(self.mem_pool, self.prefix_cache_size)
        self._reset_host_state()
        self._zero_stores()
        return active

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "num_pages": self.num_pages,
            "num_self_pages": self.num_self_pages,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "quantize_self": self.quantize_self,
            "mem_page_bytes": self.mem_page_bytes,
            "self_page_bytes": self.self_page_bytes,
            "mem_pages_in_use": self.mem_pool.in_use,
            "self_pages_in_use": self.self_pool.in_use,
            "mem_occupancy": round(self.mem_pool.occupancy, 4),
            "self_occupancy": round(self.self_pool.occupancy, 4),
            "mem_high_water": self.mem_pool.high_water,
            "self_high_water": self.self_pool.high_water,
            "mem_bytes_in_use": self.mem_pool.bytes_in_use,
            "self_bytes_in_use": self.self_pool.bytes_in_use,
            "mem_bytes_high_water": self.mem_pool.bytes_high_water,
            "self_bytes_high_water": self.self_pool.bytes_high_water,
            "mem_bytes_capacity": self.mem_pool.bytes_capacity,
            "self_bytes_capacity": self.self_pool.bytes_capacity,
            "prefix_cache": self.prefix_cache.stats(),
            "active_rows": self.active_count(),
        }
