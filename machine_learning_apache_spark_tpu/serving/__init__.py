"""Request-level serving layer over the compiled decode core.

The repo's inference story stops at ``inference.Translator`` — a one-shot,
caller-owns-the-batch API. This package adds the layer the ROADMAP's
"millions of users" north star needs: concurrent callers share a bounded
admission queue (``queue``), and a background engine (``engine``) drives
one of two KV disciplines while ``metrics`` keeps the latency/throughput
ledger (padding-waste accounting included). Entry point:
``Translator.serve()``.

- **paged** (default): a refcounted page pool + prefix cache
  (``kv_pages``) backs one device page store; a token-budget admission
  picker (``batcher.TokenBudgetBatcher``) paces chunked prefill; one
  compiled ragged decode program serves any occupancy/length mix
  (``paged_runtime``).
- **padded** (oracle/legacy): a continuous batcher groups requests into
  padded shape buckets so every batch hits an already-compiled XLA
  program (``batcher.Batcher``), and a fixed KV slot pool bounds
  in-flight decode state (``kv_slots``).
"""

from machine_learning_apache_spark_tpu.serving.batcher import (
    Batch,
    Batcher,
    TokenBudgetBatcher,
)
from machine_learning_apache_spark_tpu.serving.engine import (
    EngineStopped,
    InternalError,
    ServingEngine,
)
from machine_learning_apache_spark_tpu.serving.kv_pages import (
    NULL_PAGE,
    KVPagePool,
    PrefixCache,
    prefix_digest,
)
from machine_learning_apache_spark_tpu.serving.kv_slots import KVSlotPool
from machine_learning_apache_spark_tpu.serving.metrics import (
    Histogram,
    ServingMetrics,
)
from machine_learning_apache_spark_tpu.serving.paged_runtime import (
    PagedDecodeRuntime,
)
from machine_learning_apache_spark_tpu.serving.queue import (
    Backpressure,
    DeadlineExceeded,
    RequestQueue,
    ServeRequest,
)

__all__ = [
    "Backpressure",
    "Batch",
    "Batcher",
    "DeadlineExceeded",
    "EngineStopped",
    "Histogram",
    "InternalError",
    "KVPagePool",
    "KVSlotPool",
    "NULL_PAGE",
    "PagedDecodeRuntime",
    "PrefixCache",
    "RequestQueue",
    "ServeRequest",
    "ServingEngine",
    "ServingMetrics",
    "TokenBudgetBatcher",
    "prefix_digest",
]
