"""Request-level serving layer over the compiled decode core.

The repo's inference story stops at ``inference.Translator`` — a one-shot,
caller-owns-the-batch API. This package adds the layer the ROADMAP's
"millions of users" north star needs: concurrent callers share a bounded
admission queue (``queue``), a continuous batcher groups compatible
requests into padded shape buckets so every batch hits an
already-compiled XLA program (``batcher``), a fixed KV slot pool bounds
in-flight decode state (``kv_slots``), and a background engine drives the
cached decoders batch-by-batch (``engine``) while ``metrics`` keeps the
latency/throughput ledger. Entry point: ``Translator.serve()``.
"""

from machine_learning_apache_spark_tpu.serving.batcher import Batch, Batcher
from machine_learning_apache_spark_tpu.serving.engine import (
    EngineStopped,
    InternalError,
    ServingEngine,
)
from machine_learning_apache_spark_tpu.serving.kv_slots import KVSlotPool
from machine_learning_apache_spark_tpu.serving.metrics import (
    Histogram,
    ServingMetrics,
)
from machine_learning_apache_spark_tpu.serving.queue import (
    Backpressure,
    DeadlineExceeded,
    RequestQueue,
    ServeRequest,
)

__all__ = [
    "Backpressure",
    "Batch",
    "Batcher",
    "DeadlineExceeded",
    "EngineStopped",
    "Histogram",
    "InternalError",
    "KVSlotPool",
    "RequestQueue",
    "ServeRequest",
    "ServingEngine",
    "ServingMetrics",
]
