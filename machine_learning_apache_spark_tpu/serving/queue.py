"""Bounded admission queue — the front door of the serving engine.

Every serving system needs a place where load exceeding capacity becomes
an explicit, bounded decision instead of unbounded memory growth and
silent tail-latency collapse. ``RequestQueue`` is that place: admission
is refused with a ``Backpressure`` carrying a ``retry_after`` hint once
depth hits the bound (the client-visible contract of an HTTP 429), and
requests that outlive their deadline while still queued are failed with
``DeadlineExceeded`` rather than decoded into a response nobody is
waiting for — dead work is the first thing an overloaded server must
shed.

The queue is thread-safe and condition-backed: producers are caller
threads (``ServingEngine.submit``), the single consumer is the batcher,
which waits on the queue's condition for work. ``note_serviced`` feeds an
EWMA of observed service time back from the engine so ``retry_after``
tracks the server's actual drain rate instead of a constant.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from concurrent.futures import Future
from typing import Sequence

from machine_learning_apache_spark_tpu.telemetry import events as telemetry_events
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as telemetry_trace,
)

_REQUEST_IDS = itertools.count()
_TRACE_IDS = itertools.count()


def _new_trace_id() -> str:
    """Process-unique, gang-disambiguated request identity: the id a batch
    span records, a flight dump carries, and /statusz exemplars key on."""
    rank = telemetry_events._env_rank()
    prefix = f"r{rank}-" if rank is not None else ""
    return f"{prefix}{os.getpid():x}-{next(_TRACE_IDS):x}"


class RequestTrace:
    """One request's stitched timeline across threads: submit (caller) →
    batch/admit (worker) → first token → retire, as ``(name, t, attrs)``
    marks on the monotonic clock, plus a decode-launch counter (launches
    are counted, not itemized — a long generation spans dozens).

    Deliberately lock-free: marks are appended by one thread at a time
    (the request moves queue → worker, never concurrently), and readers
    (``/statusz`` exemplars, flight dumps) copy the append-only list.

    When a distributed trace context (``telemetry.tracectx``) is active
    on the submitting thread, the trace **adopts** its 128-bit trace id
    — so the id a replica returns in its 200 payload, the id the batch
    span links, and the id the router minted are all the same string —
    and keeps the context (``ctx``) so worker-thread emissions (the
    ``serving.request`` annotation) can re-activate it.
    """

    __slots__ = ("trace_id", "marks", "launches", "ctx")

    def __init__(self, trace_id: str | None = None, *, ctx=None):
        if ctx is None:
            ctx = telemetry_trace.current()
        self.ctx = ctx
        if trace_id is None:
            trace_id = ctx.trace_id if ctx is not None else _new_trace_id()
        self.trace_id = trace_id
        self.marks: list[tuple] = []
        self.launches = 0

    def mark(self, name: str, t: float, **attrs) -> None:
        self.marks.append((name, t, attrs or None))

    def note_launch(self, n: int = 1) -> None:
        self.launches += n

    def t(self, name: str) -> float | None:
        """Timestamp of the first mark named ``name`` (None if absent)."""
        for mark_name, t, _ in list(self.marks):
            if mark_name == name:
                return t
        return None

    def attrs(self, name: str) -> dict:
        for mark_name, _, attrs in list(self.marks):
            if mark_name == name:
                return attrs or {}
        return {}

    def breakdown(self) -> dict:
        """Queue-wait / TTFT / service / total durations derived from the
        marks — where this request's latency actually went."""
        t_submit = self.t("submit")
        t_admit = self.t("admit")
        t_first = self.t("first_token")
        t_done = self.t("complete") or self.t("failed") or self.t("expire")
        out: dict = {"trace_id": self.trace_id, "launches": self.launches}
        admit_attrs = self.attrs("admit")
        if "kind" in admit_attrs:
            out["prefill"] = admit_attrs["kind"]
        if "prefill_tokens" in admit_attrs:
            out["prefill_tokens"] = admit_attrs["prefill_tokens"]
        if t_submit is not None:
            if t_admit is not None:
                out["queue_wait_s"] = round(t_admit - t_submit, 6)
            if t_first is not None:
                out["ttft_s"] = round(t_first - t_submit, 6)
            if t_done is not None:
                out["total_s"] = round(t_done - t_submit, 6)
        if t_admit is not None and t_done is not None:
            out["service_s"] = round(t_done - t_admit, 6)
        return out

    def timeline(self) -> list[dict]:
        """The marks as dicts, with times relative to submit (JSON-ready
        — what a flight dump's quarantined-request section carries)."""
        marks = list(self.marks)
        t0 = marks[0][1] if marks else 0.0
        out = []
        for name, t, attrs in marks:
            d = {"event": name, "t_s": round(t - t0, 6)}
            if attrs:
                d.update(attrs)
            out.append(d)
        return out

    def to_dict(self) -> dict:
        return {**self.breakdown(), "timeline": self.timeline()}


class Backpressure(RuntimeError):
    """Admission refused: queue at capacity. ``retry_after`` (seconds) is
    the server's estimate of when capacity frees — the 429 Retry-After."""

    def __init__(self, depth: int, retry_after: float):
        super().__init__(
            f"queue at capacity (depth={depth}); retry after "
            f"~{retry_after:.3f}s"
        )
        self.depth = depth
        self.retry_after = retry_after


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before a result was produced."""


@dataclasses.dataclass
class ServeRequest:
    """One in-flight translation request.

    ``ids`` is the ragged (unpadded) token-id row — the bucketing key and
    the payload the batcher pads. ``deadline`` is an absolute monotonic
    time or None. The ``future`` resolves to the detokenized string (or
    an exception); timestamps feed the metrics ledger.
    """

    text: str
    ids: list[int]
    submit_time: float
    deadline: float | None = None
    id: int = dataclasses.field(default_factory=lambda: next(_REQUEST_IDS))
    future: Future = dataclasses.field(default_factory=Future)
    # Stamped by the engine: when this request's first token became
    # available (padded path: batch decode emits all tokens at once, so
    # TTFT and decode-done coincide; paged path: end of the launch that
    # produced the first emit).
    decode_done_time: float | None = None
    # Stamped by the paged engine when the request leaves the queue for a
    # cache row (queue-wait measurement point).
    admit_time: float | None = None
    slot: int | None = None
    # SLO service class ("interactive" / "batch"); None for untiered
    # direct submissions. Feeds the per-tier deadline-miss burn gauges.
    tier: str | None = None
    # The distributed-tracing identity + timeline: assigned at submit,
    # marked at every stage transition, surfaced as /statusz exemplars
    # and in quarantine flight dumps.
    trace: RequestTrace = dataclasses.field(default_factory=RequestTrace)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline

    def result(self, timeout: float | None = None) -> str:
        """Block for the translation (or re-raise its failure)."""
        return self.future.result(timeout)


class RequestQueue:
    """FIFO of pending ``ServeRequest``s with bounded depth and deadline
    hygiene. All mutation happens under one condition variable, shared
    with the batcher (``cond``) so arrival wakes a waiting consumer."""

    def __init__(
        self,
        max_depth: int,
        *,
        default_deadline_s: float | None = None,
        clock=time.monotonic,
        on_expire=None,
        on_slo=None,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self.default_deadline_s = default_deadline_s
        self.clock = clock
        # Observer for in-queue deadline deaths (the engine wires the
        # metrics ledger here so queue-level expiry is not invisible).
        self.on_expire = on_expire
        # Per-request SLO observer ``fn(tier, missed)`` — an in-queue
        # expiry is a deadline miss by definition, so the burn-rate
        # gauges must see it even though the engine never did.
        self.on_slo = on_slo
        self.cond = threading.Condition()
        self._pending: list[ServeRequest] = []
        # EWMA of per-request service time (seconds), fed by the engine;
        # seeds the retry_after estimate before any batch has completed.
        self._service_time_ewma = 0.05
        self.rejected = 0
        self.expired = 0

    # -- producer side -------------------------------------------------------
    def submit(
        self,
        text: str,
        ids: Sequence[int],
        *,
        deadline_s: float | None = None,
        tier: str | None = None,
    ) -> ServeRequest:
        """Admit a request or raise ``Backpressure``. Expired entries are
        purged first so a burst of dead requests can't hold the door shut
        against live ones."""
        now = self.clock()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        with self.cond:
            self._expire_locked(now)
            if len(self._pending) >= self.max_depth:
                self.rejected += 1
                # Cold path (admission already refused): the event is a
                # breadcrumb for the flight recorder, not a hot-loop cost.
                telemetry_events.annotate(
                    "serving.queue.reject", depth=len(self._pending)
                )
                raise Backpressure(
                    len(self._pending),
                    self._service_time_ewma * (len(self._pending) + 1),
                )
            req = ServeRequest(
                text=text,
                ids=list(ids),
                submit_time=now,
                deadline=None if deadline_s is None else now + deadline_s,
                tier=tier,
            )
            req.trace.mark("submit", now, depth=len(self._pending))
            self._pending.append(req)
            self.cond.notify_all()
            return req

    # -- consumer side (call with ``cond`` held) -----------------------------
    def pending_locked(self) -> list[ServeRequest]:
        """Live pending requests, FIFO. Caller holds ``cond``."""
        return list(self._pending)

    def take_locked(self, requests: Sequence[ServeRequest]) -> None:
        """Remove ``requests`` (a batcher's pick) from pending. Caller
        holds ``cond``."""
        chosen = {r.id for r in requests}
        self._pending = [r for r in self._pending if r.id not in chosen]

    def requeue_front(self, requests: Sequence[ServeRequest]) -> None:
        """Put admission-rollback requests back at the **head** of the
        queue in their original order (the paged engine took them but the
        page pool momentarily could not hold them). Deliberately exempt
        from the depth bound: these requests were already admitted once,
        and bouncing them now would turn a transient pool blip into
        client-visible rejections."""
        if not requests:
            return
        with self.cond:
            self._pending[:0] = list(requests)
            self.cond.notify_all()

    def _expire_locked(self, now: float) -> list[ServeRequest]:
        """Fail-and-drop every pending request whose deadline passed."""
        dead = [r for r in self._pending if r.expired(now)]
        if dead:
            self._pending = [r for r in self._pending if not r.expired(now)]
            self.expired += len(dead)
            for r in dead:
                r.trace.mark("expire", now)
                r.future.set_exception(
                    DeadlineExceeded(
                        f"request {r.id} expired after "
                        f"{now - r.submit_time:.3f}s in queue"
                    )
                )
            if self.on_expire is not None:
                self.on_expire(len(dead))
            if self.on_slo is not None:
                for r in dead:
                    self.on_slo(r.tier, True)
            telemetry_events.annotate(
                "serving.queue.expire", count=len(dead)
            )
        return dead

    def expire_overdue(self) -> int:
        """Public deadline sweep (the engine runs one per loop iteration);
        returns the number of requests dropped."""
        with self.cond:
            return len(self._expire_locked(self.clock()))

    def expire_now(self) -> int:
        """Immediate deadline sweep, callable from any thread — the
        batcher fires it when an admit round comes back empty, and the
        replica's ``/v1/cancel`` path fires it after force-expiring a
        queued request, so deadlines burn down even when no arriving
        traffic triggers the submit-side sweep. Wakes any consumer
        blocked in a timed wait so it re-evaluates the shrunken queue."""
        with self.cond:
            dead = self._expire_locked(self.clock())
            if dead:
                self.cond.notify_all()
            return len(dead)

    # -- feedback / introspection -------------------------------------------
    def note_serviced(self, n_requests: int, elapsed: float) -> None:
        """Engine feedback after each batch: fold observed per-request
        service time into the EWMA behind ``retry_after``."""
        if n_requests <= 0 or elapsed <= 0:
            return
        per_req = elapsed / n_requests
        with self.cond:
            self._service_time_ewma = (
                0.7 * self._service_time_ewma + 0.3 * per_req
            )

    @property
    def depth(self) -> int:
        with self.cond:
            return len(self._pending)

    def fail_all(self, exc: Exception) -> int:
        """Drain every pending request with ``exc`` (engine shutdown)."""
        with self.cond:
            dead, self._pending = self._pending, []
            for r in dead:
                r.future.set_exception(exc)
            self.cond.notify_all()
            return len(dead)
