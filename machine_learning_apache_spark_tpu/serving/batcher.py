"""Continuous batcher — shape-bucketed batch formation with a max-wait.

The serving engine's throughput comes from batching; its latency bound
comes from NOT batching too patiently. This module owns that trade. It
reuses the training data layer's bucketing rule (``data.bucketing.
assign_buckets``) so serving traffic lands on the same padded-shape grid
the rest of the repo compiles for: every formed batch has shape
``[max_batch, boundary]`` for some configured boundary, which means a
finite set of XLA programs, all precompilable at warmup, zero recompiles
in steady state.

Formation policy (the standard continuous-batching compromise):

- a bucket that can fill ``max_batch`` ships immediately (throughput);
- otherwise, once the OLDEST pending request has waited ``max_wait_s``,
  the bucket containing it ships partially filled (tail latency) —
  max-wait is measured against the head-of-line request, so no request
  waits more than ``max_wait_s`` for co-batching beyond its own decode;
- ties prefer the fullest bucket among those holding overdue requests.

The batcher is the queue's single consumer and blocks on the queue's
condition, waking on arrivals or timeout.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Sequence

import numpy as np

from machine_learning_apache_spark_tpu.data.bucketing import assign_buckets
from machine_learning_apache_spark_tpu.serving.queue import (
    RequestQueue,
    ServeRequest,
)


@dataclasses.dataclass
class Batch:
    """A formed batch: requests plus the padded width they share."""

    bucket: int
    boundary: int
    requests: list[ServeRequest]

    def __len__(self) -> int:
        return len(self.requests)


class Batcher:
    def __init__(
        self,
        queue: RequestQueue,
        *,
        boundaries: Sequence[int] = (16, 32, 64),
        max_batch: int = 8,
        max_wait_s: float = 0.02,
    ):
        if not boundaries:
            raise ValueError("need at least one bucket boundary")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.queue = queue
        self.boundaries = tuple(sorted(boundaries))
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s

    def bucket_of(self, ids: Sequence[int]) -> int:
        """Bucket index for one request's token row — the same smallest-
        boundary-that-fits rule training batches use."""
        return int(assign_buckets(np.asarray([len(ids)]), self.boundaries)[0])

    def _groups(
        self, pending: list[ServeRequest]
    ) -> dict[int, list[ServeRequest]]:
        groups: dict[int, list[ServeRequest]] = {}
        for r in pending:
            groups.setdefault(self.bucket_of(r.ids), []).append(r)
        return groups

    def _pick_locked(self, now: float) -> Batch | None:
        """One formation attempt over current pending state (queue cond
        held). Returns a batch or None if policy says keep waiting."""
        pending = self.queue.pending_locked()
        if not pending:
            return None
        groups = self._groups(pending)
        # Full bucket → ship (oldest-first within the bucket is free:
        # pending is FIFO, so groups preserve arrival order).
        for b, members in sorted(groups.items()):
            if len(members) >= self.max_batch:
                chosen = members[: self.max_batch]
                self.queue.take_locked(chosen)
                for r in chosen:
                    r.trace.mark("batched", now, bucket=b, full=True)
                return Batch(b, self.boundaries[b], chosen)
        # Head-of-line overdue → ship its bucket, partial.
        oldest = pending[0]
        if now - oldest.submit_time >= self.max_wait_s:
            overdue_buckets = {
                b
                for b, members in groups.items()
                if any(now - r.submit_time >= self.max_wait_s for r in members)
            }
            b = max(overdue_buckets, key=lambda k: len(groups[k]))
            chosen = groups[b][: self.max_batch]
            self.queue.take_locked(chosen)
            for r in chosen:
                r.trace.mark("batched", now, bucket=b, full=False)
            return Batch(b, self.boundaries[b], chosen)
        return None

    def next_batch(self, timeout: float | None = None) -> Batch | None:
        """Block until a batch forms (or ``timeout`` elapses → None).

        Expired requests are swept on every wake so a deadline that
        passes mid-wait fails fast instead of riding into a batch.
        """
        clock = self.queue.clock
        give_up = None if timeout is None else clock() + timeout
        with self.queue.cond:
            while True:
                now = clock()
                self.queue._expire_locked(now)
                batch = self._pick_locked(now)
                if batch is not None:
                    return batch
                # Sleep until: new arrival (notify), the head-of-line
                # request's max-wait maturing, or the caller's timeout.
                waits = []
                if give_up is not None:
                    if now >= give_up:
                        return None
                    waits.append(give_up - now)
                pending = self.queue.pending_locked()
                if pending:
                    waits.append(
                        max(
                            self.max_wait_s
                            - (now - pending[0].submit_time),
                            0.0,
                        )
                    )
                self.queue.cond.wait(min(waits) if waits else None)


class TokenBudgetBatcher:
    """Admission picker for the **paged** engine — continuous batching's
    half of the chunked-prefill compromise.

    The paged engine has no shape buckets to fill and no reason to wait:
    a free row should start decoding the oldest pending request *now*.
    What it must ration is **prefill work per decode iteration** — each
    admission runs an encode of the request's chunk-padded prompt, and
    admitting an unbounded burst between two launches would stall every
    in-flight row's next token behind a wall of prefill (the head-of-line
    blocking chunked prefill exists to prevent). So one ``take`` returns
    the longest FIFO prefix of pending requests whose summed chunk-padded
    prompt cost fits ``token_budget`` (the head request is always
    granted — a budget smaller than one prompt must not wedge the queue),
    capped at ``max_requests`` (the engine's free rows).

    Strictly FIFO: a large prompt at the head is never skipped in favour
    of cheaper ones behind it — the same no-starvation contract the page
    and slot pools enforce with ticket queues.
    """

    def __init__(self, queue: RequestQueue, *, chunk: int):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.queue = queue
        self.chunk = chunk

    def cost(self, ids: Sequence[int]) -> int:
        """Prefill cost of one request: its prompt length rounded up to
        the chunk grid — what the compiled prefill program computes."""
        n = max(len(ids), 1)
        return ((n + self.chunk - 1) // self.chunk) * self.chunk

    def take(
        self,
        *,
        max_requests: int,
        token_budget: int,
        timeout: float = 0.0,
        cost_fn=None,
    ) -> list[ServeRequest]:
        """FIFO-prefix take under the budget; blocks up to ``timeout``
        while the queue is empty (expired requests are swept on every
        wake, same as ``Batcher``). Returns [] on timeout or when
        ``max_requests`` is 0.

        ``cost_fn(request) -> int`` overrides the chunk-grid default —
        the engine uses it to price prefix-cache hits at zero, since a
        hit attaches pages without running any prefill program and so
        cannot stall in-flight rows (the thing the budget exists to
        prevent)."""
        if max_requests <= 0:
            return []
        clock = self.queue.clock
        give_up = clock() + timeout
        with self.queue.cond:
            while True:
                now = clock()
                self.queue._expire_locked(now)
                pending = self.queue.pending_locked()
                if pending:
                    chosen: list[ServeRequest] = []
                    spent = 0
                    for r in pending:
                        if len(chosen) >= max_requests:
                            break
                        c = (
                            cost_fn(r) if cost_fn is not None
                            else self.cost(r.ids)
                        )
                        if chosen and spent + c > token_budget:
                            break
                        chosen.append(r)
                        spent += c
                    self.queue.take_locked(chosen)
                    for r in chosen:
                        r.trace.mark("batched", now, budget_spent=spent)
                    return chosen
                remaining = give_up - now
                if remaining <= 0:
                    return []
                self.queue.cond.wait(remaining)
