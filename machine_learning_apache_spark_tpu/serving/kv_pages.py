"""Paged KV pool — block-table memory management for ragged serving.

The padded engine's ``KVSlotPool`` hands out whole cache *rows*; this
module manages the same capacity at **page** granularity (the Ragged
Paged Attention discipline, arxiv 2604.15464): the device holds one big
page store ``[layers, 2, num_pages, page_size, d_model]`` and every
in-flight request owns a *list* of page ids — its block table — that
grows one page at a time as decode crosses page boundaries and is freed
on EOS or deadline expiry via the owner id, exactly like the slot pool.

Two things a row pool cannot do become natural here:

- **Prefix sharing** — pages are refcounted, so N requests with the same
  prompt can point their block tables at one physical copy of the prefix
  KV. ``PrefixCache`` below keeps completed prompts' pages alive under a
  cache-owned reference (LRU, evicted under pressure) so a repeat prompt
  skips its prefill entirely.
- **Ragged occupancy** — a short request holds few pages and a long one
  many, so the pool bound is a *token* budget, not a requests × max_len
  rectangle.

Page id 0 is reserved as the **null page**: block tables are padded with
0, the kernel/scatter paths may harmlessly read/write it, and it is
never allocated. Grants are FIFO in arrival order (ticket queue), the
same starvation fix ``KVSlotPool.acquire_many`` carries.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from collections import OrderedDict, deque
from typing import Sequence

#: Reserved page id: block-table padding. Never allocated, never freed;
#: scatter/gather paths may touch it freely.
NULL_PAGE = 0


def prefix_digest(key: Sequence[int]) -> str:
    """Stable, transport-safe digest of a prefix-cache key (the prompt's
    token ids). The cache itself keys on ``tuple(ids)``; anything that
    has to ship residency over the wire — ``PrefixCache.stats()`` on
    ``/statusz``, the fleet router's affinity table — uses this digest
    instead, so two processes agree on identity without exchanging the
    ids themselves. blake2b, not ``hash()``: Python's per-process hash
    randomization would break exactly the cross-process agreement this
    exists for. Non-int elements (tests key caches with sentinel
    strings) stringify as-is — identical bytes to ``int()`` coercion
    for the production token-id case, numpy scalars included."""
    raw = ",".join(str(t) for t in key).encode("utf-8")
    return hashlib.blake2b(raw, digest_size=8).hexdigest()


class KVPagePool:
    """Refcounted free-list allocator over page ids ``1..num_pages-1``.

    Owners are any hashable id (request ids, ``("prefix", key)`` for
    cache-held references). A page is freed when its refcount reaches
    zero; ``release_owner`` drops every reference an owner holds, so the
    crash/expiry path needs only the request id.
    """

    def __init__(self, num_pages: int, *, page_bytes: int | None = None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved), got {num_pages}"
            )
        if page_bytes is not None and page_bytes < 1:
            raise ValueError(f"page_bytes must be >= 1, got {page_bytes}")
        self.num_pages = num_pages
        self.capacity = num_pages - 1  # allocatable pages
        #: Device bytes one page actually costs (payload + any
        #: quantization scales) — set by the owner so occupancy and
        #: high-water readings convert honestly to bytes regardless of
        #: the store dtype. None = owner never told us.
        self.page_bytes = page_bytes
        self._cond = threading.Condition()
        self._free = list(range(num_pages - 1, 0, -1))  # stack, page 1 on top
        self._refs: dict[int, int] = {}
        self._pages_of_owner: dict[object, list[int]] = {}
        self._tickets: deque[int] = deque()
        self._next_ticket = itertools.count()
        self.total_acquired = 0
        self.total_released = 0
        self.high_water = 0

    # -- acquisition ---------------------------------------------------------
    def try_acquire(self, n: int, owner: object) -> list[int] | None:
        """``n`` fresh pages (refcount 1) for ``owner``, or None if the
        pool can't satisfy it right now. Yields to queued blocking
        acquirers so it can't starve an earlier ``acquire``."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        with self._cond:
            if self._tickets or len(self._free) < n:
                return None
            return self._take_locked(n, owner)

    def acquire(
        self, n: int, owner: object, timeout: float | None = None
    ) -> list[int] | None:
        """Blocking all-or-nothing grant of ``n`` pages, FIFO-fair in
        arrival order (ticket queue — same fairness contract as
        ``KVSlotPool.acquire_many``)."""
        if n > self.capacity:
            raise ValueError(
                f"request for {n} pages can never fit a pool of "
                f"{self.capacity} allocatable pages"
            )
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        ticket = next(self._next_ticket)
        with self._cond:
            self._tickets.append(ticket)
            try:
                ok = self._cond.wait_for(
                    lambda: (
                        self._tickets[0] == ticket and len(self._free) >= n
                    ),
                    timeout,
                )
                if not ok:
                    return None
                return self._take_locked(n, owner)
            finally:
                self._tickets.remove(ticket)
                self._cond.notify_all()

    def _take_locked(self, n: int, owner: object) -> list[int]:
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        if pages:
            self._pages_of_owner.setdefault(owner, []).extend(pages)
        self.total_acquired += n
        self.high_water = max(self.high_water, self.in_use)
        return pages

    def add_ref(self, pages: list[int], owner: object) -> None:
        """Take an additional reference on already-allocated ``pages``
        under ``owner`` — the prefix-sharing primitive. The pages stay
        allocated until *every* holder (and the cache) releases."""
        with self._cond:
            for p in pages:
                if p == NULL_PAGE or p not in self._refs:
                    raise ValueError(f"page {p} is not allocated")
            for p in pages:
                self._refs[p] += 1
            if pages:
                self._pages_of_owner.setdefault(owner, []).extend(pages)

    # -- release -------------------------------------------------------------
    def release_owner(self, owner: object) -> int:
        """Drop every reference ``owner`` holds (EOS, expiry, crash);
        returns how many pages were actually freed (refcount hit zero).
        Idempotent — an owner with no references frees zero."""
        with self._cond:
            pages = self._pages_of_owner.pop(owner, [])
            freed = self._drop_refs_locked(pages)
            if freed:
                self._cond.notify_all()
            return freed

    def _drop_refs_locked(self, pages: list[int]) -> int:
        freed = 0
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)
                freed += 1
        self.total_released += freed
        return freed

    # -- introspection -------------------------------------------------------
    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        """Allocated fraction of the pool, 0.0-1.0."""
        return self.in_use / self.capacity

    @property
    def bytes_in_use(self) -> int | None:
        """Actual device bytes of allocated pages — dtype-aware (int8
        payload + scale planes count what they really cost), or None
        when the owner never declared ``page_bytes``."""
        return None if self.page_bytes is None else (
            self.in_use * self.page_bytes
        )

    @property
    def bytes_high_water(self) -> int | None:
        return None if self.page_bytes is None else (
            self.high_water * self.page_bytes
        )

    @property
    def bytes_capacity(self) -> int | None:
        return None if self.page_bytes is None else (
            self.capacity * self.page_bytes
        )

    def refcount(self, page: int) -> int:
        with self._cond:
            return self._refs.get(page, 0)

    def pages_of(self, owner: object) -> list[int]:
        with self._cond:
            return list(self._pages_of_owner.get(owner, []))


class PrefixCache:
    """LRU cache of prompt-prefix KV pages, keyed by token ids.

    An entry's pages carry one cache-owned reference (owner
    ``("prefix", key)``), so they survive the request that prefilled
    them. ``get`` attaches a requester reference on hit — a shared
    prefix is never freed while any attached request is decoding, and an
    evicted entry's pages only return to the pool once the last attached
    request releases. Single-writer discipline (the engine's decode
    thread) but locked anyway for introspection from other threads.
    """

    def __init__(self, pool: KVPagePool, capacity: int):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.pool = pool
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, dict] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def owner_for(key: tuple) -> tuple:
        return ("prefix", key)

    def contains(self, key: tuple) -> bool:
        """Side-effect-free membership probe — no ref attached, no LRU
        bump, no hit/miss accounting. For admission-cost estimation only;
        racy by nature (an entry can be evicted before ``get``), so
        callers must treat a True as a hint, never a reservation."""
        with self._lock:
            return key in self._entries

    def get(self, key: tuple, owner: object) -> dict | None:
        """On hit: attach ``owner`` to the entry's pages and return the
        entry ``{"pages": [...], **meta}``; on miss return None."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        self.pool.add_ref(entry["pages"], owner)
        return entry

    def put(self, key: tuple, pages: list[int], **meta) -> bool:
        """Adopt freshly-prefixed ``pages`` into the cache under a
        cache-owned reference. Returns False (no ref taken) when the
        cache is disabled or the key is already present."""
        if self.capacity == 0:
            return False
        with self._lock:
            if key in self._entries:
                return False
        self.pool.add_ref(pages, self.owner_for(key))
        with self._lock:
            # Digest computed once at adoption: stats() is scraped on
            # every /statusz poll and must not re-hash the whole cache.
            self._entries[key] = {
                "pages": list(pages), "digest": prefix_digest(key), **meta
            }
            self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            if not self.evict_one():
                break
        return True

    def evict_one(self) -> bool:
        """Drop the LRU entry's cache reference; its pages free once no
        request still holds them. False when the cache is empty."""
        with self._lock:
            if not self._entries:
                return False
            key, _entry = self._entries.popitem(last=False)
            self.evictions += 1
        self.pool.release_owner(self.owner_for(key))
        return True

    def evict_until_free(self, n_pages: int) -> None:
        """Shed LRU entries until the pool has ``n_pages`` free or the
        cache is empty — the admission path's pressure valve."""
        while self.pool.free < n_pages:
            if not self.evict_one():
                return

    def flush(self) -> int:
        """Drop every entry (quarantine path: the page store is being
        reset, so cached contents are invalid). Returns entries dropped."""
        n = 0
        while self.evict_one():
            n += 1
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self, *, max_digests: int = 64) -> dict:
        """Counters plus a bounded residency digest — the fleet router's
        affinity source of truth (scraped off ``/statusz``). Digests are
        MRU-first and capped at ``max_digests`` so a big cache can't
        bloat every scrape; the count of digests *not* listed rides
        along so a consumer can tell "bounded view" from "everything"."""
        with self._lock:
            lookups = self.hits + self.misses
            digests = [
                e["digest"] for e in reversed(self._entries.values())
            ][:max_digests]
            resident_pages = sum(
                len(e["pages"]) for e in self._entries.values()
            )
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / lookups, 4) if lookups else None,
                "resident_pages": resident_pages,
                "resident_bytes": (
                    None if self.pool.page_bytes is None
                    else resident_pages * self.pool.page_bytes
                ),
                "resident_digests": digests,
                "digests_truncated": max(0, len(self._entries) - len(digests)),
            }
