"""Serving metrics — the ledger a load balancer and an SRE both read.

Four kinds of signal, matching what the serving path actually controls:

- **admission counters** — submitted / completed / rejected / expired:
  the conservation law (submitted = completed + rejected + expired +
  in-flight) that makes lost requests visible;
- **latency histograms** — queue wait, TTFT (submit → decode done; batch
  decode emits all tokens at once, so first token and last coincide),
  total latency (submit → result set): the p50/p99 pair every latency
  SLO is written against;
- **utilization gauges** — queue depth, batch occupancy (filled rows /
  max_batch — padding waste), KV slot occupancy, sampled once per batch;
- **throughput** — generated tokens/sec over the serving window, the
  number the decode bench reports for one batch, measured here under
  concurrent load.

Histograms store raw samples (serving windows are minutes, not months —
a few thousand floats beat bucket-boundary error), and ``summary()``
returns one plain dict so `tools/serve_bench.py` can emit it verbatim
as a BENCH artifact. ``log_summary`` goes through ``utils.logging`` like
every other metric line in the repo.
"""

from __future__ import annotations

import collections
import math
import threading
import time

from machine_learning_apache_spark_tpu.telemetry import (
    events as telemetry_events,
)
from machine_learning_apache_spark_tpu.telemetry import (
    registry as telemetry_registry,
)
from machine_learning_apache_spark_tpu.telemetry import (
    tracectx as telemetry_trace,
)
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: How many slowest-request trace exemplars the ledger retains for
#: /statusz. Small on purpose: exemplars are a debugging entry point
#: ("which request was slow and where did its time go"), not a log.
_MAX_EXEMPLARS = 8

#: SLO burn-rate defaults: a 5-minute sliding window (the classic
#: fast-burn alert horizon) and an EWMA whose ~20-observation memory
#: answers "is it getting worse right now".
BURN_WINDOW_S = 300.0
BURN_ALPHA = 0.1


class ConservationError(AssertionError):
    """The serving admission ledger does not balance — a request was
    admitted and then lost without being completed, rejected, expired, or
    failed. This is the bug class the ledger exists to make impossible to
    miss."""


def percentile(samples: list[float], p: float) -> float | None:
    """Classic nearest-rank percentile (p in [0, 100]): the smallest
    sample with at least p% of the distribution at or below it. None on
    no samples."""
    if not samples:
        return None
    if not 0 <= p <= 100:
        raise ValueError(f"percentile must be in [0, 100], got {p}")
    ordered = sorted(samples)
    rank = max(0, math.ceil(p / 100 * len(ordered)) - 1)
    return ordered[rank]


class Histogram:
    """Thread-safe raw-sample histogram with percentile summaries."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._samples: list[float] = []

    def record(self, value: float) -> None:
        with self._lock:
            self._samples.append(float(value))

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._samples)

    def percentile(self, p: float) -> float | None:
        with self._lock:
            return percentile(self._samples, p)

    def summary(self) -> dict:
        with self._lock:
            s = list(self._samples)
        if not s:
            return {"count": 0}
        return {
            "count": len(s),
            "mean": sum(s) / len(s),
            "p50": percentile(s, 50),
            "p90": percentile(s, 90),
            "p99": percentile(s, 99),
            "max": max(s),
        }


class BurnRate:
    """Per-tier SLO burn gauge: what fraction of recently retired
    requests missed their deadline.

    Two views over the same observation stream, because one answers
    "how bad" and the other "which way is it going":

    - **window_rate** — miss fraction over a sliding ``window_s``-second
      window (deque of ``(ts, missed)``, pruned on write and read);
    - **ewma** — per-observation exponential average (``alpha``), the
      fast-burn trend an alert differentiates on.

    Thread-safe; observed from caller threads (rejects/expiry) and the
    decode worker (completions) concurrently. One instance per tier,
    shared shape between the serving ledger and the router ledger so the
    fleet scrape can roll replicas up without translation.
    """

    def __init__(
        self,
        *,
        window_s: float = BURN_WINDOW_S,
        alpha: float = BURN_ALPHA,
        clock=time.monotonic,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.window_s = window_s
        self.alpha = alpha
        self.clock = clock
        self._lock = threading.Lock()
        self._events: collections.deque[tuple[float, bool]] = (
            collections.deque()
        )
        self._ewma: float | None = None
        self._total = 0
        self._missed = 0

    def observe(self, missed: bool) -> None:
        now = self.clock()
        with self._lock:
            self._events.append((now, bool(missed)))
            self._prune_locked(now)
            self._total += 1
            self._missed += int(bool(missed))
            x = 1.0 if missed else 0.0
            self._ewma = (
                x if self._ewma is None
                else (1 - self.alpha) * self._ewma + self.alpha * x
            )

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def snapshot(self) -> dict:
        """One JSON-able reading: lifetime totals, windowed miss rate,
        and the EWMA trend (both None before any observation)."""
        now = self.clock()
        with self._lock:
            self._prune_locked(now)
            n = len(self._events)
            misses = sum(1 for _, m in self._events if m)
            return {
                "window_s": self.window_s,
                "window_count": n,
                "window_missed": misses,
                "window_rate": round(misses / n, 4) if n else None,
                "ewma": None if self._ewma is None else round(self._ewma, 4),
                "total": self._total,
                "missed": self._missed,
            }

    @property
    def ewma(self) -> float:
        with self._lock:
            return 0.0 if self._ewma is None else self._ewma


class ServingMetrics:
    """One instance per engine; every field is safe to bump from the
    submit path (caller threads) and the worker thread concurrently."""

    def __init__(self, *, clock=time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self.started_at = clock()
        # admission counters
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.expired = 0
        # expired_in_flight ⊆ expired — requests reaped *mid-decode* by
        # the engine's between-launch deadline sweep (including remote
        # /v1/cancel force-expiry), as opposed to expiring in queue.
        # Dead work the cancellation path actually saved, made visible.
        self.expired_in_flight = 0
        self.failed = 0
        # containment counters (engine._quarantine / supervisor restart):
        # quarantined ⊆ failed — requests failed by a contained batch
        # fault; loop_restarts counts decode-loop deaths the supervisor
        # caught. Both 0 in a healthy window.
        self.quarantined = 0
        self.loop_restarts = 0
        # throughput
        self.batches = 0
        self.tokens_out = 0
        # padding-waste accounting: of every token slot the compiled
        # programs computed (prefill + decode), how many carried a real
        # token? The padded path pays rectangle slots (max_batch x
        # boundary, max_batch x max_new_tokens); the paged path pays
        # chunk-padded prefill and max_active x steps launches. The gap
        # is the waste the paged KV layer exists to shrink.
        self.real_tokens = 0
        self.padded_tokens = 0
        # latency histograms (seconds)
        self.queue_wait = Histogram("queue_wait_s")
        self.ttft = Histogram("ttft_s")
        self.total_latency = Histogram("total_latency_s")
        self.batch_latency = Histogram("batch_latency_s")
        # utilization gauges, sampled per batch
        self.batch_occupancy = Histogram("batch_occupancy")
        self.slot_occupancy = Histogram("slot_occupancy")
        self.queue_depth = Histogram("queue_depth")
        # slowest-request trace exemplars: list of (total_s, trace dict),
        # kept sorted slowest-first, capped at _MAX_EXEMPLARS.
        self._exemplars: list[tuple[float, dict]] = []
        # Per-tier SLO burn gauges, created on a tier's first observed
        # retirement. Each tier's EWMA is mirrored into the registry as
        # ``mlspark_serving_slo_burn_<tier>`` so /metrics exposes the
        # fast-burn signal with no extra registration step.
        self._burn: dict[str, BurnRate] = {}
        self._burn_gauges: dict[str, object] = {}
        # Mirror the admission counters into the process-global telemetry
        # registry (no-op singletons when MLSPARK_TELEMETRY=0). The registry
        # is cumulative across engines in one process — the Prometheus view;
        # this ledger stays per-engine.
        reg = telemetry_registry.get_registry()
        self._reg_counters = {
            name: reg.counter("serving", name)
            for name in (
                "submitted", "completed", "rejected", "expired", "failed",
                "quarantined", "loop_restarts", "batches", "tokens_out",
                "real_tokens", "padded_tokens",
            )
        }

    # -- event hooks ---------------------------------------------------------
    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1
        self._reg_counters["submitted"].inc()

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        self._reg_counters["rejected"].inc()

    def on_expire(self, n: int = 1, *, in_flight: bool = False) -> None:
        with self._lock:
            self.expired += n
            if in_flight:
                self.expired_in_flight += n
        self._reg_counters["expired"].inc(n)

    def on_failure(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n
        self._reg_counters["failed"].inc(n)

    def on_quarantine(self, n: int = 1) -> None:
        with self._lock:
            self.quarantined += n
        self._reg_counters["quarantined"].inc(n)

    def on_loop_restart(self) -> None:
        with self._lock:
            self.loop_restarts += 1
        self._reg_counters["loop_restarts"].inc()

    def on_batch(
        self,
        *,
        n_requests: int,
        max_batch: int,
        decode_s: float,
        new_tokens: int,
        queue_depth: int,
        slot_occupancy: float,
    ) -> None:
        with self._lock:
            self.batches += 1
            self.tokens_out += new_tokens
        self._reg_counters["batches"].inc()
        self._reg_counters["tokens_out"].inc(new_tokens)
        self.batch_latency.record(decode_s)
        self.batch_occupancy.record(n_requests / max_batch)
        self.queue_depth.record(queue_depth)
        self.slot_occupancy.record(slot_occupancy)

    def on_token_slots(self, *, real: int, padded: int) -> None:
        """Account one program dispatch's token slots: ``padded`` slots
        computed, of which ``real`` carried live tokens (``real <=
        padded`` by construction). Cache-hit prefills compute nothing and
        contribute (0, 0)."""
        if real > padded:
            raise ValueError(
                f"real tokens ({real}) cannot exceed computed slots "
                f"({padded})"
            )
        with self._lock:
            self.real_tokens += real
            self.padded_tokens += padded
        self._reg_counters["real_tokens"].inc(real)
        self._reg_counters["padded_tokens"].inc(padded)
        # Event-stream mirror so the gang-level telemetry report
        # (telemetry.aggregate.serving_report) can compute waste across
        # ranks from merged rank files.
        if telemetry_events.enabled():
            log_ = telemetry_events.get_log()
            log_.emit("counter", "serving.tokens_real", value=float(real))
            log_.emit(
                "counter", "serving.tokens_padded", value=float(padded)
            )

    def on_complete(self, *, queue_wait: float, ttft: float, total: float) -> None:
        with self._lock:
            self.completed += 1
        self._reg_counters["completed"].inc()
        self.queue_wait.record(queue_wait)
        self.ttft.record(ttft)
        self.total_latency.record(total)

    def on_slo(self, tier: str | None, missed: bool) -> None:
        """Fold one retired request into its tier's deadline-miss burn
        gauge. ``tier=None`` (untiered direct submission) counts under
        ``interactive`` — the standalone engine's implicit class."""
        tier = tier or "interactive"
        with self._lock:
            burn = self._burn.get(tier)
            if burn is None:
                burn = self._burn[tier] = BurnRate(clock=self.clock)
                self._burn_gauges[tier] = (
                    telemetry_registry.get_registry().gauge(
                        "serving", f"slo_burn_{tier}"
                    )
                )
            gauge = self._burn_gauges[tier]
        burn.observe(missed)
        gauge.set(burn.ewma)

    def slo(self) -> dict:
        """Per-tier burn-gauge snapshots ({} before any observation) —
        the ``slo`` section /statusz and the fleet scrape read."""
        with self._lock:
            burns = dict(self._burn)
        return {tier: b.snapshot() for tier, b in sorted(burns.items())}

    def on_trace(self, req) -> None:
        """Fold one retired request's trace into the ledger: keep it if it
        is among the slowest seen (the /statusz exemplars), and mirror its
        latency breakdown into the event stream as a ``serving.request``
        annotation so gang-level reports can aggregate request latency
        across ranks from merged rank files. Emitted under the request's
        distributed trace context (when it has one) so the annotation
        stitches into the cross-process trace."""
        trace = getattr(req, "trace", None)
        if trace is None:
            return
        bd = trace.breakdown()
        total = bd.get("total_s")
        if total is None:
            return
        with self._lock:
            self._exemplars.append((total, trace.to_dict()))
            self._exemplars.sort(key=lambda e: e[0], reverse=True)
            del self._exemplars[_MAX_EXEMPLARS:]
        if telemetry_events.enabled():
            with telemetry_trace.use(getattr(trace, "ctx", None)):
                telemetry_events.get_log().emit(
                    "annotation", "serving.request", value=total, attrs=bd
                )

    def request_exemplars(self) -> list[dict]:
        """The slowest retired requests' trace dicts, slowest first."""
        with self._lock:
            return [dict(t) for _, t in self._exemplars]

    def ledger(self) -> dict:
        """One atomic read of the admission counters plus the derived
        ``in_flight`` — the /statusz view of the conservation law. Taken
        under the ledger lock so the equality holds even when scraped
        mid-decode (no counter can move between the reads)."""
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "quarantined": self.quarantined,
                "loop_restarts": self.loop_restarts,
            }
        out["in_flight"] = (
            out["submitted"] - out["completed"] - out["rejected"]
            - out["expired"] - out["failed"]
        )
        return out

    # -- invariants ----------------------------------------------------------
    def check_conservation(self, *, in_flight: int = 0) -> dict:
        """Assert the admission conservation law::

            submitted == completed + rejected + expired + failed + in_flight

        Every admission attempt increments ``submitted`` (the engine counts
        BEFORE the queue decides), so each must end in exactly one terminal
        bucket — ``failed`` includes the quarantined and engine-stop
        failures. ``in_flight`` is the caller's count of requests still
        being worked (0 after a full drain). Raises ``ConservationError``
        with the full ledger on imbalance; returns the ledger otherwise.
        """
        with self._lock:
            ledger = {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "quarantined": self.quarantined,
                "in_flight": in_flight,
            }
        accounted = (
            ledger["completed"] + ledger["rejected"] + ledger["expired"]
            + ledger["failed"] + in_flight
        )
        if ledger["submitted"] != accounted:
            raise ConservationError(
                f"serving conservation violated: submitted "
                f"{ledger['submitted']} != completed + rejected + expired "
                f"+ failed + in_flight = {accounted} ({ledger})"
            )
        return ledger

    # -- reporting -----------------------------------------------------------
    @property
    def tokens_per_sec(self) -> float:
        elapsed = self.clock() - self.started_at
        return self.tokens_out / elapsed if elapsed > 0 else 0.0

    @property
    def padding_waste(self) -> float | None:
        """Fraction of computed token slots that carried padding, 0-1
        (None before any slots are accounted)."""
        with self._lock:
            if self.padded_tokens == 0:
                return None
            return 1.0 - self.real_tokens / self.padded_tokens

    def summary(self) -> dict:
        waste = self.padding_waste
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "rejected": self.rejected,
            "expired": self.expired,
            "expired_in_flight": self.expired_in_flight,
            "failed": self.failed,
            "quarantined": self.quarantined,
            "loop_restarts": self.loop_restarts,
            "batches": self.batches,
            "tokens_out": self.tokens_out,
            "tokens_per_sec": round(self.tokens_per_sec, 1),
            "real_tokens": self.real_tokens,
            "padded_tokens": self.padded_tokens,
            "padding_waste": None if waste is None else round(waste, 4),
            "queue_wait_s": self.queue_wait.summary(),
            "ttft_s": self.ttft.summary(),
            "total_latency_s": self.total_latency.summary(),
            "batch_latency_s": self.batch_latency.summary(),
            "batch_occupancy": self.batch_occupancy.summary(),
            "slot_occupancy": self.slot_occupancy.summary(),
            "queue_depth": self.queue_depth.summary(),
            "slo": self.slo(),
        }

    def log_summary(self) -> dict:
        s = self.summary()
        log.info(
            "serving: %d completed / %d submitted (%d rejected, %d expired,"
            " %d failed) | %d batches, %d tokens @ %.1f tok/s | total p50 %s"
            " p99 %s | batch occupancy p50 %s | padding waste %s",
            s["completed"], s["submitted"], s["rejected"], s["expired"],
            s["failed"], s["batches"], s["tokens_out"], s["tokens_per_sec"],
            _fmt(s["total_latency_s"].get("p50")),
            _fmt(s["total_latency_s"].get("p99")),
            _fmt(s["batch_occupancy"].get("p50")),
            _fmt(s["padding_waste"]),
        )
        return s


def _fmt(v: float | None) -> str:
    return "n/a" if v is None else f"{v:.4f}"
