"""Length bucketing — ragged text batching without wasted FLOPs.

The reference sidesteps raggedness by padding everything to one fixed length
(128 for AG_NEWS, exactly 200 for Multi30k — SURVEY.md §7 hard parts), so a
12-token sentence burns the same compute as a 200-token one. XLA wants
static shapes, but it does not want *one* shape: bucketing pads each batch
to the smallest boundary that fits it — a handful of distinct XLA programs
(one compile each), and attention/scan FLOPs scale with the bucket, not the
corpus maximum.

``BucketByLengthLoader`` groups examples by length into boundary buckets,
shuffles within buckets per epoch (``set_epoch`` contract), and yields
``(ids[B, boundary], *extras)`` batches in a bucket-interleaved order.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import numpy as np

from machine_learning_apache_spark_tpu.data.text import PAD_ID, PadToLength


def assign_buckets(
    lengths: np.ndarray, boundaries: Sequence[int]
) -> np.ndarray:
    """Index of the smallest boundary ≥ length; longer sequences land in the
    last bucket (and are truncated to it at padding time)."""
    boundaries = np.asarray(sorted(boundaries))
    return np.minimum(
        np.searchsorted(boundaries, np.asarray(lengths)),
        len(boundaries) - 1,
    )


class BucketByLengthLoader:
    """Minibatches of bucket-padded token ids (plus parallel extras).

    >>> loader = BucketByLengthLoader(pipe.ragged(texts), labels,
    ...                               batch_size=32,
    ...                               boundaries=(32, 64, 128))
    >>> for ids, lbls in loader: ...   # ids.shape[1] ∈ {32, 64, 128}

    ``drop_last=True`` drops each bucket's ragged tail so every batch of a
    bucket shares one shape. Batch order interleaves buckets
    deterministically per epoch (seeded), so training sees a mix of lengths
    rather than all-short-then-all-long.

    Sequences longer than the largest boundary are an error unless
    ``truncate_overlong=True`` (the same eos-clipping guard
    ``TextPipeline`` applies to ``fixed_len``).

    ``num_replicas``/``rank`` (defaulting to the JAX process layout, like
    ``DistributedSampler``) give each rank a disjoint per-epoch slice of
    every bucket — the loader honors the same sharding contract as the
    rest of the data layer.
    """

    def __init__(
        self,
        sequences: Sequence[Sequence[int]],
        *extras: np.ndarray,
        batch_size: int,
        boundaries: Sequence[int] = (32, 64, 128),
        pad_id: int = PAD_ID,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        truncate_overlong: bool = False,
        num_replicas: int | None = None,
        rank: int | None = None,
        lengths: Sequence[int] | None = None,
    ) -> None:
        if not boundaries:
            raise ValueError("need at least one bucket boundary")
        for e in extras:
            if len(e) != len(sequences):
                raise ValueError(
                    f"extra array length {len(e)} != {len(sequences)}"
                )
        self.sequences = [list(s) for s in sequences]
        self.extras = tuple(np.asarray(e) for e in extras)
        self.batch_size = batch_size
        self.boundaries = tuple(sorted(boundaries))
        self.pad_id = pad_id
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_replicas = (
            num_replicas if num_replicas is not None else jax.process_count()
        )
        self.rank = rank if rank is not None else jax.process_index()
        if not (0 <= self.rank < self.num_replicas):
            raise ValueError(f"rank {self.rank} outside [0, {self.num_replicas})")
        self._epoch = 0
        # ``lengths`` overrides the bucketing key (paired loaders bucket by
        # the max across their streams); padding still uses real row lengths.
        if lengths is not None and len(lengths) != len(self.sequences):
            raise ValueError(
                f"lengths ({len(lengths)}) != sequences ({len(self.sequences)})"
            )
        lengths = np.asarray(
            [len(s) for s in self.sequences] if lengths is None else lengths
        )
        longest = int(lengths.max(initial=0))
        if longest > self.boundaries[-1] and not truncate_overlong:
            raise ValueError(
                f"sequence of length {longest} exceeds the largest bucket "
                f"boundary {self.boundaries[-1]}; tokens (incl. eos) would "
                "be silently clipped — raise the boundary or pass "
                "truncate_overlong=True"
            )
        bucket_ids = assign_buckets(lengths, self.boundaries)
        self._buckets = [
            np.flatnonzero(bucket_ids == i) for i in range(len(self.boundaries))
        ]

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def _pad(self, idx: np.ndarray, width: int) -> np.ndarray:
        rows = PadToLength(width, self.pad_id)(
            [self.sequences[i] for i in idx]
        )
        return np.asarray(rows, dtype=np.int32)

    def _rank_slice(self, order: np.ndarray) -> np.ndarray:
        """This rank's share of one bucket's (permuted) members, padded by
        wrapping so every rank gets the same count — the equal-count
        invariant collectives depend on (``DistributedSampler`` semantics).
        The same seed on every rank keeps the slices consistent."""
        if len(order) == 0:
            return order
        per_rank = -(-len(order) // self.num_replicas)
        wrapped = np.resize(order, per_rank * self.num_replicas)
        return wrapped[self.rank :: self.num_replicas]

    def _schedule(self, epoch: int) -> list[tuple[int, np.ndarray]]:
        """One epoch's (bucket, example-indices) batch list — the single
        source of truth for __iter__/__len__/padding_efficiency."""
        rng = np.random.default_rng(self.seed + epoch)
        batches: list[tuple[int, np.ndarray]] = []
        for b, members in enumerate(self._buckets):
            order = rng.permutation(members) if self.shuffle else members
            order = self._rank_slice(order)
            stop = (
                len(order) - self.batch_size + 1
                if self.drop_last
                else len(order)
            )
            for start in range(0, max(stop, 0), self.batch_size):
                batches.append((b, order[start : start + self.batch_size]))
        if self.shuffle:
            batches = [batches[i] for i in rng.permutation(len(batches))]
        return batches

    def __iter__(self):
        for b, idx in self._schedule(self._epoch):
            ids = self._pad(idx, self.boundaries[b])
            yield (ids, *(e[idx] for e in self.extras))

    def __len__(self) -> int:
        return len(self._schedule(self._epoch))

    @property
    def padding_efficiency(self) -> float:
        """Real tokens / padded slots over this epoch's actual batches —
        the FLOP-waste metric bucketing improves (1.0 = no padding)."""
        real = padded = 0
        for b, idx in self._schedule(self._epoch):
            width = self.boundaries[b]
            real += sum(min(len(self.sequences[i]), width) for i in idx)
            padded += len(idx) * width
        return real / padded if padded else 1.0


class BucketByLengthPairsLoader(BucketByLengthLoader):
    """Paired-stream bucketing for translation: each (src, trg) pair lands
    in the smallest boundary that fits ``max(len(src), len(trg) - 1)``, src
    pads to the boundary and trg to ``boundary + 1`` (so the teacher-forced
    decoder input ``trg[:, :-1]`` is boundary-wide) — the SURVEY.md §7
    recommendation: keep XLA's static shapes (one program per bucket) but
    stop paying corpus-max attention FLOPs on short sentence pairs.

    Yields ``(src_ids[B, b], trg_ids[B, b + 1], *extras)`` batches.
    """

    def __init__(
        self,
        src_sequences: Sequence[Sequence[int]],
        trg_sequences: Sequence[Sequence[int]],
        *extras: np.ndarray,
        **kwargs,
    ) -> None:
        if len(src_sequences) != len(trg_sequences):
            raise ValueError(
                f"{len(src_sequences)} src vs {len(trg_sequences)} trg rows"
            )
        self.trg_sequences = [list(t) for t in trg_sequences]
        kwargs.setdefault(
            "lengths",
            [
                max(len(s), len(t) - 1)
                for s, t in zip(src_sequences, trg_sequences)
            ],
        )
        super().__init__(src_sequences, *extras, **kwargs)

    def _pad_trg(self, idx: np.ndarray, width: int) -> np.ndarray:
        rows = PadToLength(width, self.pad_id)(
            [self.trg_sequences[i] for i in idx]
        )
        return np.asarray(rows, dtype=np.int32)

    def __iter__(self):
        for b, idx in self._schedule(self._epoch):
            width = self.boundaries[b]
            yield (
                self._pad(idx, width),
                self._pad_trg(idx, width + 1),
                *(e[idx] for e in self.extras),
            )

    @property
    def padding_efficiency(self) -> float:
        """Across BOTH streams (src slots + trg slots)."""
        real = padded = 0
        for b, idx in self._schedule(self._epoch):
            width = self.boundaries[b]
            for i in idx:
                real += min(len(self.sequences[i]), width)
                real += min(len(self.trg_sequences[i]), width + 1)
            padded += len(idx) * (2 * width + 1)
        return real / padded if padded else 1.0
