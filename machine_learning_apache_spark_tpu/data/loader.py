"""Batch loader: host arrays → fixed-shape device-ready batches.

The reference's DataLoaders (batch=30/32, ``pytorch_multilayer_perceptron.py:76-81``)
iterate torch tensors; here batches are numpy views stacked to *static shapes*
(XLA recompiles per shape — ragged tails either drop or pad, never retrace).

TPU-first delta (SURVEY.md §7 hard parts): all preprocessing happens at
construction/collation time on the host, never inside the step; the loop
overlaps host batch prep with device compute because the jitted step is
dispatched async.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Sequence

import numpy as np

from machine_learning_apache_spark_tpu.data.sampler import DistributedSampler


class ArrayDataset:
    """``TensorDataset`` equivalent (``pytorch_multilayer_perceptron.py:70``):
    parallel arrays indexed together."""

    def __init__(self, *arrays: np.ndarray) -> None:
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError(f"length mismatch: {[len(a) for a in arrays]}")
        self.arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, idx):
        if (
            isinstance(idx, np.ndarray)
            and idx.ndim == 1
            and np.issubdtype(idx.dtype, np.integer)
        ):
            # Batch assembly goes through the native threaded row-gather
            # when built (numpy fancy indexing otherwise) — the loader's
            # host-side hot path (native/batch_gather.cpp).
            from machine_learning_apache_spark_tpu.native import gather_rows

            return tuple(gather_rows(a, idx) for a in self.arrays)
        return tuple(a[idx] for a in self.arrays)


def random_split(
    dataset: ArrayDataset, lengths_or_fracs: Sequence[float], seed: int = 0
) -> list[ArrayDataset]:
    """``torch.utils.data.random_split`` equivalent
    (``pytorch_multilayer_perceptron.py:73`` does a 60/40 split).

    torch semantics for disambiguation: integer entries are absolute lengths,
    float entries are fractions — never guessed from the sum (``[1]`` on a
    10-row dataset means one split of length 1, not 100%).
    """
    n = len(dataset)
    values = np.asarray(lengths_or_fracs)
    if np.issubdtype(values.dtype, np.integer):  # absolute lengths given
        sizes = values.astype(int)
        if sizes.sum() != n:
            raise ValueError(f"lengths {sizes.tolist()} != dataset size {n}")
    else:
        fracs = values.astype(np.float64)
        if fracs.sum() > 1.0 + 1e-9:
            raise ValueError(
                f"fractions {fracs.tolist()} sum to {fracs.sum()} > 1; pass "
                "integers for absolute lengths"
            )
        sizes = (fracs / fracs.sum() * n).astype(int)
        sizes[-1] = n - sizes[:-1].sum()
    perm = np.random.default_rng(seed).permutation(n)
    out, start = [], 0
    for s in sizes:
        idx = perm[start : start + s]
        out.append(ArrayDataset(*(a[idx] for a in dataset.arrays)))
        start += s
    return out


class DataLoader:
    """Minibatch iterator over an ArrayDataset.

    - ``sampler``: a DistributedSampler for rank-sliced epochs; otherwise an
      internal (optionally shuffled) full-range order.
    - ``drop_last=True`` keeps every batch the same shape (one XLA program).
    - ``collate``: optional ``fn(tuple_of_arrays) -> batch pytree`` applied per
      batch on the host (the tokenize-outside-the-step seam; the reference
      tokenizes *inside* its hot loop, ``pytorch_lstm.py:148``).
    - ``prefetch``: assemble up to N batches ahead on a background thread,
      overlapping host batch prep with async-dispatched device steps
      (SURVEY.md §7 hard parts: input pipelines off the hot path).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        *,
        shuffle: bool = False,
        sampler: DistributedSampler | None = None,
        drop_last: bool = True,
        seed: int = 0,
        collate: Callable[[tuple], Any] | None = None,
        prefetch: int = 0,
    ) -> None:
        if shuffle and sampler is not None:
            raise ValueError(
                "shuffle and sampler are mutually exclusive; give the sampler "
                "shuffle=True instead"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.sampler = sampler
        self.drop_last = drop_last
        self.seed = seed
        self.collate = collate
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        self.prefetch = prefetch
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _order(self) -> np.ndarray:
        if self.sampler is not None:
            return np.fromiter(iter(self.sampler), dtype=np.int64)
        if self.shuffle:
            return np.random.default_rng(self.seed + self._epoch).permutation(
                len(self.dataset)
            )
        return np.arange(len(self.dataset))

    def _batches(self) -> Iterator:
        order = self._order()
        stop = (
            len(order) - self.batch_size + 1 if self.drop_last else len(order)
        )
        for start in range(0, max(stop, 0), self.batch_size):
            idx = order[start : start + self.batch_size]
            batch = self.dataset[idx]
            yield self.collate(batch) if self.collate else batch

    def __iter__(self) -> Iterator:
        if self.prefetch > 0:
            return _prefetch_iter(self._batches(), self.prefetch)
        return self._batches()

    def __len__(self) -> int:
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)


def _prefetch_iter(it: Iterator, depth: int) -> Iterator:
    """Pull ``it`` on a background thread into a bounded queue.

    The TPU step is dispatched async, so the device computes while Python
    prepares the NEXT batch — but only if that prep isn't serialized behind
    the dispatch loop. A daemon thread assembles batches ahead (gather /
    tokenize-collate release the GIL in the native paths), bounded at
    ``depth`` to cap host memory. Worker exceptions re-raise at the
    consuming ``next()``.
    """
    import queue as _queue
    import threading

    q: _queue.Queue = _queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END, _ERR = object(), object()

    def _put(item) -> bool:
        # Bounded-wait put so an abandoned consumer (mid-epoch exception,
        # next(iter(loader)) peek) doesn't leave this thread blocked forever
        # pinning `depth` batches — the stop event is honored within 100ms.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not _put(item):
                    return
        except BaseException as e:  # re-raised on the consumer side
            _put((_ERR, e))
        else:
            _put(_END)

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        # Runs on normal exhaustion, consumer exception, and GeneratorExit
        # (abandonment): release the worker and drop queued batches.
        stop.set()
        try:
            while True:
                q.get_nowait()
        except _queue.Empty:
            pass
