"""Dataset loaders — the reference's four data families (SURVEY.md §1 L1).

Real-file loaders cover the formats the reference pulls via
torchvision/torchtext (FashionMNIST idx files, AG_NEWS csv, Multi30k parallel
text); each has a clearly-named *synthetic* generator with the same shape and
a learnable structure, used when the files are absent (this image has no
network egress — mirroring the reference's ``download=True`` is not possible,
``pytorch_cnn.py:53-69``).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from machine_learning_apache_spark_tpu.data.frame import ArrayFrame

# ---------------------------------------------------------------- image (idx)


def _read_idx(path: str) -> np.ndarray:
    """MNIST/FashionMNIST idx format (optionally .gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def load_fashion_mnist(root: str, train: bool = True) -> ArrayFrame:
    """FashionMNIST from idx files under ``root`` — the torchvision layout
    (``pytorch_cnn.py:53-69``). Images come back ``[N, 28, 28, 1]`` float32 in
    [0, 1] (NHWC + the ``ToTensor()`` scaling), labels int64."""
    prefix = "train" if train else "t10k"
    candidates = [
        os.path.join(root, "FashionMNIST", "raw"),
        os.path.join(root, "fashion-mnist"),
        root,
    ]
    for base in candidates:
        for ext in ("", ".gz"):
            img_p = os.path.join(base, f"{prefix}-images-idx3-ubyte{ext}")
            lbl_p = os.path.join(base, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(img_p) and os.path.exists(lbl_p):
                images = _read_idx(img_p).astype(np.float32) / 255.0
                labels = _read_idx(lbl_p).astype(np.int64)
                return ArrayFrame(images[..., None], labels)
    raise FileNotFoundError(
        f"FashionMNIST idx files not found under {root!r}; "
        "use synthetic_image_classification for an offline stand-in"
    )


def synthetic_image_classification(
    n: int = 2048,
    *,
    height: int = 28,
    width: int = 28,
    channels: int = 1,
    num_classes: int = 10,
    seed: int = 0,
) -> ArrayFrame:
    """FashionMNIST-shaped learnable synthetic set: each class is a bright
    axis-aligned bar whose position/orientation encode the label, plus noise.
    A TinyVGG reaches high accuracy in a few epochs — the loss/accuracy
    *trajectory* contract of BASELINE.md without the download."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    images = rng.normal(0.1, 0.08, (n, height, width, channels)).astype(np.float32)
    band = max(2, height // num_classes)
    for i, lbl in enumerate(labels):
        if lbl % 2 == 0:  # horizontal bar at class-dependent row
            r = (lbl // 2) * band % (height - band)
            images[i, r : r + band, :, :] += 0.8
        else:  # vertical bar at class-dependent column
            c = (lbl // 2) * band % (width - band)
            images[i, :, c : c + band, :] += 0.8
    return ArrayFrame(np.clip(images, 0.0, 1.0), labels.astype(np.int64))


def load_cifar10(root: str, train: bool = True) -> ArrayFrame:
    """CIFAR-10 from the binary-version layout (``cifar-10-batches-bin/
    data_batch_{1..5}.bin`` + ``test_batch.bin``; each record is 1 label
    byte + 3072 CHW pixel bytes) — the BASELINE.json distributed-CNN
    workload shape. Images come back ``[N, 32, 32, 3]`` float32 in [0, 1]
    (NHWC + the ``ToTensor()`` scaling), labels int64."""
    candidates = [
        os.path.join(root, "cifar-10-batches-bin"),
        os.path.join(root, "CIFAR10", "cifar-10-batches-bin"),
        root,
    ]
    names = (
        [f"data_batch_{i}.bin" for i in range(1, 6)]
        if train
        else ["test_batch.bin"]
    )
    for base in candidates:
        paths = [os.path.join(base, n) for n in names]
        exists = [os.path.exists(p) for p in paths]
        if not any(exists):
            continue
        # Leading contiguous prefix only, loudly: real CIFAR-10 has 5 train
        # batches, and silently training on whatever subset survived an
        # interrupted download would misrepresent the run. (The committed
        # fixture intentionally ships just data_batch_1.bin.)
        k = 0
        while k < len(exists) and exists[k]:
            k += 1
        present = paths[:k]
        if not present:
            raise FileNotFoundError(
                f"{paths[0]} is missing but later batch files exist under "
                f"{base!r}; refusing a gapped CIFAR-10 read"
            )
        if train and (k < 5 or any(exists[k:])):
            from machine_learning_apache_spark_tpu.utils.logging import (
                get_logger,
            )

            get_logger(__name__).warning(
                "loading %d of 5 CIFAR-10 train batches from %s (files "
                "beyond the leading prefix are missing or gapped)", k, base,
            )
        images, labels = [], []
        for p in present:
            raw = np.fromfile(p, dtype=np.uint8)
            if raw.size % 3073:
                raise ValueError(
                    f"{p}: size {raw.size} is not a whole number of "
                    "3073-byte CIFAR-10 records"
                )
            rec = raw.reshape(-1, 3073)
            labels.append(rec[:, 0].astype(np.int64))
            images.append(
                rec[:, 1:]
                .reshape(-1, 3, 32, 32)  # stored CHW
                .transpose(0, 2, 3, 1)  # → NHWC
                .astype(np.float32)
                / 255.0
            )
        return ArrayFrame(np.concatenate(images), np.concatenate(labels))
    raise FileNotFoundError(
        f"CIFAR-10 binary batches not found under {root!r}; use "
        "synthetic_image_classification(height=32, width=32, channels=3) "
        "for an offline stand-in"
    )


# ---------------------------------------------------------------- tabular


def synthetic_multiclass(
    n: int = 150, *, num_features: int = 4, num_classes: int = 3, seed: int = 0
) -> ArrayFrame:
    """The MLlib sample's shape (4 features, 3 classes,
    ``mllib_multilayer_perceptron_classifier.py:32``) as Gaussian class blobs
    — linearly separable enough that the 4-5-4-3 MLP reaches high accuracy
    with the reference recipe (SGD 0.03, 100 epochs)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    centers = rng.normal(0.0, 2.0, (num_classes, num_features))
    features = centers[labels] + rng.normal(0.0, 0.6, (n, num_features))
    return ArrayFrame(features.astype(np.float32), labels.astype(np.int64))


# ---------------------------------------------------------------- text (clf)

_TOPIC_WORDS = {
    0: "government election minister parliament treaty policy senate law".split(),
    1: "match team season coach player score league tournament".split(),
    2: "market shares profit revenue investor bank earnings trade".split(),
    3: "software chip research quantum network robot data science".split(),
}
_FILLER = "the a of and to in on with for said new over from".split()


def synthetic_text_classification(
    n: int = 2000, *, num_classes: int = 4, min_len: int = 8, max_len: int = 24,
    seed: int = 0,
) -> tuple[list[str], np.ndarray]:
    """AG_NEWS-shaped (4-class news text, ``pytorch_lstm.py:46-47``): raw
    strings whose topical vocabulary determines the label. Returned as
    (texts, labels) so the full tokenizer→vocab→transform pipeline (C13) is
    exercised on real strings."""
    assert num_classes <= len(_TOPIC_WORDS)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    texts = []
    for lbl in labels:
        length = rng.integers(min_len, max_len + 1)
        words = [
            str(rng.choice(_TOPIC_WORDS[int(lbl)]))
            if rng.random() < 0.6
            else str(rng.choice(_FILLER))
            for _ in range(length)
        ]
        texts.append(" ".join(words))
    return texts, labels.astype(np.int64)


def load_ag_news(root: str, train: bool = True) -> tuple[list[str], np.ndarray]:
    """AG_NEWS from the torchtext csv layout (``class,title,description``),
    labels remapped 1-4 → 0-3."""
    path = os.path.join(root, "AG_NEWS", "train.csv" if train else "test.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found; use synthetic_text_classification offline"
        )
    import csv

    texts, labels = [], []
    with open(path, newline="") as f:
        for row in csv.reader(f):
            labels.append(int(row[0]) - 1)
            texts.append(" ".join(row[1:]))
    return texts, np.asarray(labels, dtype=np.int64)


# ---------------------------------------------------------------- translation

_SRC_WORDS = (
    "man woman dog cat child house tree street ball book water sky bird car "
    "red green small big old young runs walks sees holds likes near under a the"
).split()
# Deterministic word-for-word mapping to a synthetic target language —
# learnable by a seq2seq model, Multi30k-shaped (en→de pairs,
# pytorch_machine_translator.py:14-17).
_TRG_MAP = {w: f"{w[::-1]}zn" for w in _SRC_WORDS}


def synthetic_translation_pairs(
    n: int = 2000, *, min_len: int = 4, max_len: int = 12, seed: int = 0
) -> list[tuple[str, str]]:
    rng = np.random.default_rng(seed)
    pairs = []
    for _ in range(n):
        length = rng.integers(min_len, max_len + 1)
        src_words = [str(rng.choice(_SRC_WORDS)) for _ in range(length)]
        trg_words = [_TRG_MAP[w] for w in src_words]
        pairs.append((" ".join(src_words), " ".join(trg_words)))
    return pairs


def load_multi30k(root: str, split: str = "train") -> list[tuple[str, str]]:
    """Multi30k from the torchtext parallel-file layout
    (``train.en``/``train.de``)."""
    en = os.path.join(root, "multi30k", f"{split}.en")
    de = os.path.join(root, "multi30k", f"{split}.de")
    if not (os.path.exists(en) and os.path.exists(de)):
        raise FileNotFoundError(
            f"multi30k files not found under {root!r}; "
            "use synthetic_translation_pairs offline"
        )
    with open(en) as fe, open(de) as fd:
        return list(zip((l.strip() for l in fe), (l.strip() for l in fd)))
