"""Spark-style reader: ``session.read.format("libsvm").load(path)``.

Mirrors the ingestion call at ``mllib_multilayer_perceptron_classifier.py:22-23``.
Supported formats: ``libsvm`` (dense ArrayFrame), ``npz`` (features/labels
arrays saved by numpy), ``csv`` (last column = label).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from machine_learning_apache_spark_tpu.data.frame import ArrayFrame
from machine_learning_apache_spark_tpu.data.libsvm import read_libsvm


class DataReader:
    def __init__(self, session: Any = None) -> None:
        self._session = session
        self._format = "libsvm"
        self._options: dict[str, Any] = {}

    def format(self, fmt: str) -> "DataReader":
        self._format = fmt.lower()
        return self

    def option(self, key: str, value: Any) -> "DataReader":
        self._options[key.lower()] = value
        return self

    def load(self, path: str) -> ArrayFrame:
        if self._format == "libsvm":
            nf = self._options.get("numfeatures")
            return read_libsvm(path, num_features=int(nf) if nf else None)
        if self._format == "npz":
            data = np.load(path)
            return ArrayFrame(data["features"], data["labels"])
        if self._format == "csv":
            raw = np.loadtxt(path, delimiter=",", dtype=np.float32)
            return ArrayFrame(raw[:, :-1], raw[:, -1].astype(np.int64))
        if self._format == "image":
            from machine_learning_apache_spark_tpu.data.datasets import (
                load_fashion_mnist,
            )

            split = str(self._options.get("split", "train")).lower()
            if split not in ("train", "test", "t10k"):
                raise ValueError(
                    f"image split must be 'train' or 'test', got {split!r}"
                )
            return load_fashion_mnist(path, train=split == "train")
        raise ValueError(f"unsupported format {self._format!r}")
