"""libsvm format reader/writer.

The reference ingests ``sample_multiclass_classification_data.txt`` via
``spark.read.format("libsvm").load(path)``
(``mllib_multilayer_perceptron_classifier.py:22-23``): lines of
``<label> <index>:<value> ...`` with 1-based sparse indices, materialized as
4-feature/3-class dense rows (``pytorch_multilayer_perceptron.py:56-66``).

Two parsers: a pure-Python fallback and a C++ fast path
(``native/libsvm_parser.cpp``) used automatically when its shared library has
been built — the reference's equivalent parser is Spark JVM native code, so
the framework's is native too (SURVEY.md §2.2).
"""

from __future__ import annotations

import numpy as np

from machine_learning_apache_spark_tpu.data.frame import ArrayFrame


def _parse_python(text: str) -> tuple[np.ndarray, np.ndarray, int]:
    labels: list[float] = []
    rows: list[list[tuple[int, float]]] = []
    max_index = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        try:
            labels.append(float(parts[0]))
            row: list[tuple[int, float]] = []
            for item in parts[1:]:
                idx_s, val_s = item.split(":", 1)
                idx = int(idx_s)
                if idx < 1:
                    raise ValueError(f"libsvm indices are 1-based, got {idx}")
                row.append((idx, float(val_s)))
                max_index = max(max_index, idx)
            rows.append(row)
        except (ValueError, IndexError) as e:
            raise ValueError(f"malformed libsvm line {lineno}: {line!r}") from e
    n = len(rows)
    dense = np.zeros((n, max_index), dtype=np.float32)
    for i, row in enumerate(rows):
        for idx, val in row:
            dense[i, idx - 1] = val
    return dense, np.asarray(labels), max_index


def read_libsvm(
    path: str, *, num_features: int | None = None, use_native: bool | None = None
) -> ArrayFrame:
    """Read a libsvm file into a dense ArrayFrame.

    ``num_features`` pads/validates the feature dimension (Spark's
    ``numFeatures`` option). ``use_native=None`` auto-selects the C++ parser
    when built.
    """
    if use_native is None or use_native:
        try:
            from machine_learning_apache_spark_tpu.native import libsvm_native

            result = libsvm_native.parse_file(path)
        except (ImportError, OSError):
            if use_native:
                raise
            result = None
        if result is not None:
            features, labels = result
            return _finalize(features, labels, num_features)

    with open(path) as f:
        features, labels, _ = _parse_python(f.read())
    return _finalize(features, labels, num_features)


def _finalize(
    features: np.ndarray, labels: np.ndarray, num_features: int | None
) -> ArrayFrame:
    if num_features is not None:
        if features.shape[1] > num_features:
            raise ValueError(
                f"file has feature index {features.shape[1]} > num_features={num_features}"
            )
        if features.shape[1] < num_features:
            pad = np.zeros(
                (features.shape[0], num_features - features.shape[1]), np.float32
            )
            features = np.concatenate([features, pad], axis=1)
    # Labels in the MLlib sample are 0/1/2 floats; store as int64 class ids
    # (the bridge at pytorch_multilayer_perceptron.py:66 does .long()).
    return ArrayFrame(features.astype(np.float32), labels.astype(np.int64))


def write_libsvm(path: str, features: np.ndarray, labels: np.ndarray) -> None:
    """Write dense rows in libsvm format (1-based indices, zeros skipped)."""
    with open(path, "w") as f:
        for row, label in zip(np.asarray(features), np.asarray(labels)):
            items = " ".join(
                f"{i + 1}:{v:.6g}" for i, v in enumerate(row) if v != 0.0
            )
            lbl = f"{float(label):g}"
            f.write(f"{lbl} {items}\n".rstrip() + "\n")
