"""Sequence packing — multiple sentence pairs per row, zero cross-talk.

The reference pads every Multi30k pair to exactly 200×200
(``pytorch_machine_translator.py:70-98``); typical pairs are ~15 tokens, so
>90% of every attention matrix and LM-head matmul is pad work. Length
bucketing (``data.bucketing``) shrinks the row; packing goes further: fill
the fixed row with SEVERAL pairs, separated by segment ids, and train on
one static shape with almost no pad.

Correctness contract (pinned by ``tests/test_packing.py``): a pair packed
into segment *j* of a row sees exactly what it would see alone —
block-diagonal segment masks (``ops.masks.make_segment_mask``) confine
encoder self-, decoder self- (∧ causal), and cross-attention to the pair's
own tokens; per-token position ids restart at 0 per segment so positional
encodings match the unpacked run; and the teacher-forcing loss mask drops
the boundary position where segment *j*'s last token would otherwise
"predict" segment *j+1*'s first.

TPU rationale: packing preserves the one-static-shape property XLA wants
(unlike dynamic batching) while raising the useful-token density of every
matmul — the standard input-side lever of pod-scale LLM training, applied
to the reference's seq2seq workload.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np


@dataclass
class PackedPairs:
    """Fixed-shape packed arrays (all ``[rows, length]`` int32).

    ``*_segments``: 1..k per row, 0 = pad. ``*_positions``: within-segment
    offsets (0 for pad). ``pair_count``: total pairs packed;
    ``token_efficiency``: non-pad fraction of the packed token grid vs the
    one-pair-per-row layout's.
    """

    src: np.ndarray
    src_segments: np.ndarray
    src_positions: np.ndarray
    trg: np.ndarray
    trg_segments: np.ndarray
    trg_positions: np.ndarray
    pair_count: int
    token_efficiency: float
    unpacked_efficiency: float
    # Pairs excluded because truncation left no attendable src or no
    # scorable trg (<2 tokens). Can't trigger on the standard SOS/EOS
    # pipeline, but raw-id callers need the signal — silent corpus
    # shrinkage would otherwise only show as a reduced pair_count.
    dropped_pairs: int = 0

    def arrays(self) -> tuple[np.ndarray, ...]:
        return (
            self.src, self.src_segments, self.src_positions,
            self.trg, self.trg_segments, self.trg_positions,
        )


def pack_translation_pairs(
    src_rows: Sequence[Sequence[int]],
    trg_rows: Sequence[Sequence[int]],
    *,
    src_len: int,
    trg_len: int,
    pad_id: int = 0,
    max_segments: int | None = None,
) -> PackedPairs:
    """Greedily pack ragged (src, trg) id-list pairs into fixed rows.

    Next-fit in corpus order (deterministic, no reordering, earlier rows
    never revisited — simpler and more stream-friendly than first-fit, at
    some packing-density cost): a pair joins the open row only when BOTH
    its streams fit the remaining src/trg budgets (a pair must live in one
    row — its cross-attention needs its source alongside). Over-long
    streams are truncated to the row budget. ``max_segments`` caps pairs
    per row (None = unlimited).
    """
    if len(src_rows) != len(trg_rows):
        raise ValueError(
            f"src/trg pair count mismatch: {len(src_rows)} vs {len(trg_rows)}"
        )
    if src_len < 1 or trg_len < 2:
        # trg needs >= 2 so teacher forcing (input trg[:-1], labels trg[1:])
        # has at least one scored position.
        raise ValueError(f"row budgets too small: src {src_len}, trg {trg_len}")

    rows: list[tuple[list[list[int]], list[list[int]]]] = []
    open_src: list[list[int]] = []
    open_trg: list[list[int]] = []
    used_s = used_t = 0

    def flush():
        nonlocal open_src, open_trg, used_s, used_t
        if open_src:
            rows.append((open_src, open_trg))
        open_src, open_trg, used_s, used_t = [], [], 0, 0

    dropped = 0
    for s, t in zip(src_rows, trg_rows):
        s = list(s)[:src_len]
        t = list(t)[:trg_len]
        if not s or len(t) < 2:
            dropped += 1  # nothing attendable / nothing scorable
            continue
        full = (
            used_s + len(s) > src_len
            or used_t + len(t) > trg_len
            or (max_segments is not None and len(open_src) >= max_segments)
        )
        if full:
            flush()
        open_src.append(s)
        open_trg.append(t)
        used_s += len(s)
        used_t += len(t)
    flush()

    n = len(rows)
    out = PackedPairs(
        src=np.full((n, src_len), pad_id, dtype=np.int32),
        src_segments=np.zeros((n, src_len), dtype=np.int32),
        src_positions=np.zeros((n, src_len), dtype=np.int32),
        trg=np.full((n, trg_len), pad_id, dtype=np.int32),
        trg_segments=np.zeros((n, trg_len), dtype=np.int32),
        trg_positions=np.zeros((n, trg_len), dtype=np.int32),
        pair_count=sum(len(r[0]) for r in rows),
        token_efficiency=0.0,
        unpacked_efficiency=0.0,
        dropped_pairs=dropped,
    )
    tokens = 0
    for i, (srcs, trgs) in enumerate(rows):
        for stream, ids_lists in (("src", srcs), ("trg", trgs)):
            arr = getattr(out, stream)
            seg = getattr(out, f"{stream}_segments")
            pos = getattr(out, f"{stream}_positions")
            cursor = 0
            for j, ids in enumerate(ids_lists, start=1):
                arr[i, cursor : cursor + len(ids)] = ids
                seg[i, cursor : cursor + len(ids)] = j
                pos[i, cursor : cursor + len(ids)] = np.arange(len(ids))
                cursor += len(ids)
        tokens += sum(len(x) for x in srcs) + sum(len(x) for x in trgs)
    grid = n * (src_len + trg_len)
    out.token_efficiency = tokens / grid if grid else 0.0
    unpacked_grid = out.pair_count * (src_len + trg_len)
    out.unpacked_efficiency = tokens / unpacked_grid if unpacked_grid else 0.0
    return out
