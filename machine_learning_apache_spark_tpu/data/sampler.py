"""Distributed sampling — reference L3 with correct semantics.

The reference partitions data with ``DistributedSampler`` +
``sampler.set_epoch(epoch)`` (``distributed_cnn.py:112-119,168``) but
hardcodes ``num_replicas=2, rank=0`` on every rank (quirk Q3) so all ranks
read shard 0. Correct semantics here: replicas/rank default from the runtime,
every rank gets a disjoint contiguous slice of an epoch-seeded permutation.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np


class DistributedSampler:
    """Epoch-seeded, rank-sliced index sampler.

    ``num_replicas``/``rank`` default to the JAX process layout (the correct
    form of ``DistributedSampler(num_replicas=world, rank=rank)``); ``seed``
    plus ``set_epoch`` reproduce torch's cross-epoch shuffling contract.
    ``drop_last=False`` pads by wrapping (torch's default) so every rank
    yields the same count — a collective-friendly invariant.
    """

    def __init__(
        self,
        dataset_size: int,
        num_replicas: int | None = None,
        rank: int | None = None,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        self.dataset_size = dataset_size
        self.num_replicas = (
            num_replicas if num_replicas is not None else jax.process_count()
        )
        self.rank = rank if rank is not None else jax.process_index()
        if not (0 <= self.rank < self.num_replicas):
            raise ValueError(f"rank {self.rank} outside [0, {self.num_replicas})")
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_size // self.num_replicas
        else:
            self.num_samples = -(-dataset_size // self.num_replicas)

    def set_epoch(self, epoch: int) -> None:
        """Reseed the permutation for a new epoch (``sampler.set_epoch``,
        ``distributed_multilayer_perceptron.py:124``)."""
        self.epoch = epoch

    def _global_order(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        total = self.num_samples * self.num_replicas
        if self.drop_last:
            order = order[:total]
        elif total > len(order):
            # Wrap as many times as needed (dataset may be smaller than the
            # replica count) so every rank yields exactly num_samples — the
            # equal-count invariant collectives depend on.
            order = np.resize(order, total)
        return order

    def __iter__(self) -> Iterator[int]:
        # Interleaved assignment (rank, rank+R, rank+2R, ...) like torch —
        # with a shuffled order the distinction is immaterial, but unshuffled
        # evaluation keeps ranks' loads balanced over sorted datasets.
        return iter(self._global_order()[self.rank :: self.num_replicas])

    def __len__(self) -> int:
        return self.num_samples
