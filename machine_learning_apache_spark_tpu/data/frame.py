"""ArrayFrame — the minimal DataFrame stand-in at the ingestion boundary.

The reference's ingestion hands a Spark DataFrame upward, whose only consumed
operations are ``randomSplit`` (``mllib_multilayer_perceptron_classifier.py:27``),
``.toPandas()`` + per-row densify (``pytorch_multilayer_perceptron.py:56-66``),
and ``count``. ArrayFrame holds dense host arrays (features, labels) and
provides exactly that surface; "toPandas→stack" collapses into ``arrays()``
because data is already dense and columnar.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ArrayFrame:
    """Columnar (features, labels) with Spark-DataFrame-shaped helpers."""

    features: np.ndarray
    labels: np.ndarray

    def __post_init__(self) -> None:
        if len(self.features) != len(self.labels):
            raise ValueError(
                f"features/labels length mismatch: {len(self.features)} vs {len(self.labels)}"
            )

    def __len__(self) -> int:
        return len(self.labels)

    def count(self) -> int:
        return len(self)

    @property
    def num_features(self) -> int:
        return int(self.features.shape[-1])

    @property
    def num_classes(self) -> int:
        return int(self.labels.max()) + 1 if len(self.labels) else 0

    def random_split(
        self, weights: list[float], seed: int = 0
    ) -> list["ArrayFrame"]:
        """``DataFrame.randomSplit(weights, seed)`` equivalent
        (``mllib_multilayer_perceptron_classifier.py:27`` uses
        ``[0.6, 0.4], seed=1234``): shuffle once, split by normalized
        weights."""
        total = float(sum(weights))
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self))
        out: list[ArrayFrame] = []
        start = 0
        for i, w in enumerate(weights):
            if i == len(weights) - 1:
                stop = len(self)
            else:
                stop = start + int(round(len(self) * w / total))
            idx = perm[start:stop]
            out.append(ArrayFrame(self.features[idx], self.labels[idx]))
            start = stop
        return out

    randomSplit = random_split

    def arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """The Spark→tensor bridge endpoint (reference C10): dense float32
        features + int64 labels, ready for ``device_put``."""
        return (
            np.asarray(self.features, dtype=np.float32),
            np.asarray(self.labels, dtype=np.int64),
        )
