"""Text preprocessing — tokenizer, vocab, transform chains (reference C13).

The reference builds its text pipelines twice, inline (SURVEY.md §1 L2):

- classification: ``get_tokenizer('basic_english')`` → vocab via
  ``build_vocab_from_iterator`` with specials ``['<pad>','<sos>','<eos>',
  '<unk>']``, ``special_first=True``, default index ``<unk>`` →
  ``VocabTransform → AddToken(sos, begin=True) → Truncate(128) →
  AddToken(eos, begin=False) → ToTensor(padding_value=0)``
  (``pytorch_lstm.py:51-83``, ``distributed_lstm.py:81-107``);
- translation: spacy en/de tokenizers, two vocabs, same chain but
  ``Truncate(199)`` + ``PadTransform(200, <pad>)`` so every sentence is
  exactly length 200 (``pytorch_machine_translator.py:20-98``).

Here the pipeline is one reusable module. Tokenization is pluggable (the
spacy-equivalent seam, SURVEY.md §2.2) with a ``basic_english`` default, and
everything happens *before* the compiled step — the reference tokenizes inside
the hot loop (``pytorch_lstm.py:148``, ``pytorch_machine_translator.py:156-161``),
which would starve a TPU (SURVEY.md §7 hard parts: input pipelines off the
hot path). Outputs are fixed-shape ``np.int32`` arrays, XLA-friendly.

Correctness deltas recorded in SURVEY.md §2.5: the vocab's default index is
its *own* ``<unk>`` (Q11 used a cross-vocab index), and ``padding_idx``
semantics use index 0 = ``<pad>`` (Q10 passed the token string ``'0'``).
"""

from __future__ import annotations

import re
from collections import Counter
from collections.abc import Callable, Iterable, Sequence

import numpy as np

# Special tokens, in the reference's order (special_first=True,
# ``pytorch_lstm.py:58-67``): indices 0..3.
PAD, SOS, EOS, UNK = "<pad>", "<sos>", "<eos>", "<unk>"
SPECIALS = (PAD, SOS, EOS, UNK)
PAD_ID, SOS_ID, EOS_ID, UNK_ID = 0, 1, 2, 3

# ------------------------------------------------------------------ tokenizers

# torchtext's basic_english: lowercase, punctuation split off as own tokens.
_BASIC_PATTERNS = [
    (re.compile(r"\'"), " '  "),
    (re.compile(r"\""), ""),
    (re.compile(r"\."), " . "),
    (re.compile(r"<br \/>"), " "),
    (re.compile(r","), " , "),
    (re.compile(r"\("), " ( "),
    (re.compile(r"\)"), " ) "),
    (re.compile(r"\!"), " ! "),
    (re.compile(r"\?"), " ? "),
    (re.compile(r"\;"), " "),
    (re.compile(r"\:"), " "),
    (re.compile(r"\s+"), " "),
]


def basic_english(text: str) -> list[str]:
    """The ``get_tokenizer('basic_english')`` rule set (``pytorch_lstm.py:51``):
    lowercase, strip double quotes, split sentence punctuation into their own
    tokens, collapse whitespace."""
    text = text.lower()
    for pattern, repl in _BASIC_PATTERNS:
        text = pattern.sub(repl, text)
    return text.split()


_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


def word_punct(text: str) -> list[str]:
    """Language-neutral word/punctuation splitter — the pluggable stand-in for
    the reference's spacy ``de_core_news_sm``/``en_core_web_sm`` models
    (``pytorch_machine_translator.py:20-21``); spacy is not required."""
    return _WORD_RE.findall(text.lower())


_TOKENIZERS: dict[str, Callable[[str], list[str]]] = {
    "basic_english": basic_english,
    "word_punct": word_punct,
}


def register_tokenizer(
    name: str, fn: Callable[[str], list[str]], *, overwrite: bool = False
) -> None:
    """Register a custom tokenizer under ``name`` so pipelines built with it
    reconstruct by name — the requirement ``inference.Translator.save`` /
    ``Classifier.save`` enforce (a bare callable cannot be rebuilt by
    ``load()`` in a fresh process; re-register before loading there too).

    Shadowing a built-in (or an earlier registration) raises unless
    ``overwrite=True`` — a silent swap would tokenize differently than the
    vocab was built with.
    """
    if not callable(fn):
        raise TypeError(f"tokenizer must be callable, got {fn!r}")
    if name in _TOKENIZERS and _TOKENIZERS[name] is not fn and not overwrite:
        raise ValueError(
            f"tokenizer {name!r} is already registered; pass overwrite=True "
            "to replace it"
        )
    _TOKENIZERS[name] = fn


def get_tokenizer(name: str | Callable[[str], list[str]]) -> Callable[[str], list[str]]:
    """Resolve a tokenizer by name or pass a callable through — the
    ``torchtext.data.utils.get_tokenizer`` surface."""
    if callable(name):
        return name
    try:
        return _TOKENIZERS[name]
    except KeyError:
        raise ValueError(
            f"unknown tokenizer {name!r}; available: {sorted(_TOKENIZERS)}"
        ) from None


# ------------------------------------------------------------------ vocabulary


class Vocab:
    """Token ↔ id mapping with specials-first layout and an OOV default.

    Mirrors the ``build_vocab_from_iterator(..., specials=[...],
    special_first=True)`` + ``set_default_index(vocab['<unk>'])`` contract
    (``pytorch_lstm.py:55-67``). Lookup of an unknown token returns
    ``default_index`` — this vocab's own ``<unk>`` (fixing quirk Q11).
    """

    def __init__(
        self,
        tokens: Sequence[str],
        specials: Sequence[str] = SPECIALS,
        default_index: int | None = None,
    ):
        special_set = set(specials)
        self._itos: list[str] = list(specials) + [
            t for t in dict.fromkeys(tokens) if t not in special_set
        ]
        self._stoi: dict[str, int] = {t: i for i, t in enumerate(self._itos)}
        if default_index is None:
            default_index = self._stoi.get(UNK, 0)
        self.default_index = default_index

    @classmethod
    def build_from_iterator(
        cls,
        iterator: Iterable[Sequence[str]],
        *,
        min_freq: int = 1,
        specials: Sequence[str] = SPECIALS,
        max_tokens: int | None = None,
    ) -> "Vocab":
        """Frequency-then-lexical ordering, matching torchtext's
        ``build_vocab_from_iterator`` semantics used at
        ``pytorch_lstm.py:55-58`` and ``pytorch_machine_translator.py:53-67``."""
        counter: Counter[str] = Counter()
        for tokens in iterator:
            counter.update(tokens)
        for s in specials:
            counter.pop(s, None)
        ordered = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        if max_tokens is not None:
            ordered = ordered[: max(0, max_tokens - len(specials))]
        kept = [t for t, c in ordered if c >= min_freq]
        return cls(kept, specials=specials)

    def __len__(self) -> int:
        return len(self._itos)

    def __contains__(self, token: str) -> bool:
        return token in self._stoi

    def __getitem__(self, token: str) -> int:
        return self._stoi.get(token, self.default_index)

    def lookup_token(self, index: int) -> str:
        return self._itos[index]

    def lookup_indices(self, tokens: Sequence[str]) -> list[int]:
        return [self[t] for t in tokens]

    def lookup_tokens(self, indices: Sequence[int]) -> list[str]:
        return [self._itos[i] for i in indices]

    @property
    def itos(self) -> list[str]:
        return list(self._itos)


# ------------------------------------------------------------------ transforms
#
# Each transform maps list-of-token-id-lists → list-of-token-id-lists (ragged),
# except ToArray which pads to a rectangle. Composed with Sequential — the
# ``torchtext.transforms.Sequential`` chain shape (``pytorch_lstm.py:70-83``).


class VocabTransform:
    """tokens → ids (``T.VocabTransform``, ``pytorch_lstm.py:79``)."""

    def __init__(self, vocab: Vocab):
        self.vocab = vocab

    def __call__(self, batch: Sequence[Sequence[str]]) -> list[list[int]]:
        return [self.vocab.lookup_indices(toks) for toks in batch]


class AddToken:
    """Prepend/append a token id (``T.AddToken(1, begin=True)`` /
    ``T.AddToken(2, begin=False)``, ``pytorch_lstm.py:80-82``)."""

    def __init__(self, token_id: int, begin: bool):
        self.token_id, self.begin = token_id, begin

    def __call__(self, batch: Sequence[Sequence[int]]) -> list[list[int]]:
        if self.begin:
            return [[self.token_id, *ids] for ids in batch]
        return [[*ids, self.token_id] for ids in batch]


class Truncate:
    """Clip to ``max_seq_len`` (``T.Truncate(128)``, ``pytorch_lstm.py:76``)."""

    def __init__(self, max_seq_len: int):
        self.max_seq_len = max_seq_len

    def __call__(self, batch: Sequence[Sequence[int]]) -> list[list[int]]:
        return [list(ids[: self.max_seq_len]) for ids in batch]


class PadToLength:
    """Right-pad every sequence to exactly ``length`` (``T.PadTransform(200,
    pad_value)``, ``pytorch_machine_translator.py:82,97``) — the fixed-shape
    contract XLA wants (SURVEY.md §7: static shapes)."""

    def __init__(self, length: int, pad_value: int = PAD_ID):
        self.length, self.pad_value = length, pad_value

    def __call__(self, batch: Sequence[Sequence[int]]) -> list[list[int]]:
        return [
            list(ids[: self.length]) + [self.pad_value] * (self.length - len(ids))
            for ids in batch
        ]


class ToArray:
    """Ragged → rectangular ``np.int32`` padded with ``padding_value``
    (``T.ToTensor(padding_value=0)``, ``pytorch_lstm.py:83``)."""

    def __init__(self, padding_value: int = PAD_ID):
        self.padding_value = padding_value

    def __call__(self, batch: Sequence[Sequence[int]]) -> np.ndarray:
        if not batch:
            return np.zeros((0, 0), dtype=np.int32)
        width = max(len(ids) for ids in batch)
        out = np.full((len(batch), width), self.padding_value, dtype=np.int32)
        for i, ids in enumerate(batch):
            out[i, : len(ids)] = ids
        return out


class Sequential:
    """Left-to-right transform composition (``T.Sequential``)."""

    def __init__(self, *transforms):
        self.transforms = transforms

    def __call__(self, batch):
        for t in self.transforms:
            batch = t(batch)
        return batch


# ------------------------------------------------------------------ pipelines


class TextPipeline:
    """tokenizer + vocab + transform chain as one precomputation unit.

    ``__call__`` takes raw strings and returns a rectangular id array —
    everything the reference did per-batch *inside* the training loop, hoisted
    out so device steps see only ready tensors.
    """

    def __init__(
        self,
        vocab: Vocab,
        tokenizer: str | Callable[[str], list[str]] = "basic_english",
        *,
        max_seq_len: int = 128,
        fixed_len: int | None = None,
        add_sos: bool = True,
        add_eos: bool = True,
    ):
        if fixed_len is not None and fixed_len < max_seq_len + int(add_eos):
            raise ValueError(
                f"fixed_len={fixed_len} cannot hold max_seq_len={max_seq_len} "
                f"tokens{' + eos' if add_eos else ''}; eos would be clipped"
            )
        self.tokenizer = get_tokenizer(tokenizer)
        self.vocab = vocab
        # Reconstruction spec (inference.Translator.save/load): everything
        # needed to rebuild this pipeline around a saved vocab. A callable
        # tokenizer is recorded by name and must be re-registered on load.
        self.spec = {
            "tokenizer": (
                tokenizer
                if isinstance(tokenizer, str)
                else getattr(tokenizer, "__name__", "custom")
            ),
            "max_seq_len": max_seq_len,
            "fixed_len": fixed_len,
            "add_sos": add_sos,
            "add_eos": add_eos,
        }
        steps: list = [VocabTransform(vocab)]
        if add_sos:
            steps.append(AddToken(SOS_ID, begin=True))
        steps.append(Truncate(max_seq_len))
        if add_eos:
            steps.append(AddToken(EOS_ID, begin=False))
        if fixed_len is not None:
            steps.append(PadToLength(fixed_len, PAD_ID))
        steps.append(ToArray(PAD_ID))
        self.transform = Sequential(*steps)
        import threading

        self._native_vocab: tuple[int, int] | None = None  # (pid, handle)
        self._native_vocab_lock = threading.Lock()

    def _encode_native(self, texts: Sequence[str]) -> np.ndarray | None:
        """C++ fast path (native.text_native): one pass over the batch for
        the built-in tokenizers on ASCII text with a fixed output width.
        Returns None whenever any gate fails — the Python chain is always
        the semantic reference (parity pinned by tests/test_native.py)."""
        import os as _os

        from machine_learning_apache_spark_tpu.utils import env as envcfg

        if envcfg.get_bool("MLSPARK_NO_NATIVE_TEXT"):
            return None
        # Only for the ACTUAL built-in functions — comparing against the
        # registry entry would pass a custom tokenizer registered over a
        # builtin name before the pipeline was built, silently encoding
        # with builtin semantics against a custom-tokenized vocab.
        if self.tokenizer is basic_english:
            mode = 0
        elif self.tokenizer is word_punct:
            mode = 1
        else:
            return None
        if self.spec["fixed_len"] is None or not texts:
            return None
        if not all(isinstance(t, str) and t.isascii() for t in texts):
            return None
        try:
            from machine_learning_apache_spark_tpu.native import text_native
        except ImportError:
            return None
        try:
            pid = _os.getpid()
            with self._native_vocab_lock:
                if self._native_vocab is None or self._native_vocab[0] != pid:
                    itos = self.vocab.itos
                    if any("\n" in t for t in itos):
                        return None  # '\n' is the handle blob's separator
                    # Handles are process-local: rebuild after fork (each
                    # process has its own registry copy). Freed at pipeline
                    # GC via weakref.finalize so long-lived processes that
                    # build many pipelines don't accumulate C++ vocab maps.
                    import weakref

                    handle = text_native.vocab_handle(itos)
                    weakref.finalize(self, text_native.vocab_free, handle)
                    self._native_vocab = (pid, handle)
            return text_native.encode(
                self._native_vocab[1],
                list(texts),
                mode=mode,
                max_seq_len=self.spec["max_seq_len"],
                fixed_len=self.spec["fixed_len"],
                add_sos=self.spec["add_sos"],
                add_eos=self.spec["add_eos"],
                sos_id=SOS_ID,
                eos_id=EOS_ID,
                pad_id=PAD_ID,
                default_index=self.vocab.default_index,
            )
        except (ImportError, RuntimeError, OSError):
            return None  # never fail the pipeline over the fast path

    def __call__(self, texts: Sequence[str]) -> np.ndarray:
        # Materialize once: the native gate iterates texts (isascii scan)
        # and a one-shot generator must not be exhausted before encoding.
        texts = list(texts)
        arr = self._encode_native(texts)
        if arr is not None:
            return arr
        return self.transform([self.tokenizer(t) for t in texts])

    def __getstate__(self):
        # Native handle and its lock are process-local, unpicklable state.
        d = self.__dict__.copy()
        d["_native_vocab"] = None
        d.pop("_native_vocab_lock", None)
        return d

    def __setstate__(self, d):
        import threading

        self.__dict__.update(d)
        self._native_vocab = None
        self._native_vocab_lock = threading.Lock()

    def ragged(self, texts: Sequence[str]) -> list[list[int]]:
        """Token-id lists *before* rectangularization — the input to length
        bucketing (``data.bucketing``), which pads per-bucket instead of
        per-corpus."""
        batch = [self.tokenizer(t) for t in texts]
        for t in self.transform.transforms:
            if isinstance(t, (PadToLength, ToArray)):
                continue
            batch = t(batch)
        return batch

    @classmethod
    def fit(
        cls,
        texts: Iterable[str],
        tokenizer: str | Callable[[str], list[str]] = "basic_english",
        *,
        min_freq: int = 1,
        max_tokens: int | None = None,
        **kwargs,
    ) -> "TextPipeline":
        """Build vocab over ``texts`` then return the ready pipeline — the
        one-call equivalent of the reference's vocab-build + chain-build
        blocks (``pytorch_lstm.py:55-83``)."""
        tok = get_tokenizer(tokenizer)
        vocab = Vocab.build_from_iterator(
            (tok(t) for t in texts), min_freq=min_freq, max_tokens=max_tokens
        )
        # Pass the ORIGINAL argument through (init re-resolves): a string
        # name must reach the reconstruction spec as the registry key, not
        # as the resolved function's __name__.
        return cls(vocab, tokenizer=tokenizer, **kwargs)


def classification_pipeline(
    texts: Iterable[str], *, max_seq_len: int = 128, **kwargs
) -> TextPipeline:
    """The AG_NEWS chain: sos + truncate(max_seq_len) + eos, ragged-padded
    (``pytorch_lstm.py:70-83``; default max_seq_len=128 per ``:76``)."""
    return TextPipeline.fit(
        texts, "basic_english", max_seq_len=max_seq_len, **kwargs
    )


def translation_pipelines(
    pairs: Sequence[tuple[str, str]],
    *,
    max_len: int = 200,
    trg_max_len: int | None = None,
    tokenizer: str | Callable[[str], list[str]] = "word_punct",
    **kwargs,
) -> tuple[TextPipeline, TextPipeline]:
    """The Multi30k dual-vocab chains: truncate(max_len-1) + eos + pad to
    exactly ``max_len`` (``pytorch_machine_translator.py:70-98``). Returns
    (src_pipeline, trg_pipeline) with *separate* vocabs, each defaulting to
    its own ``<unk>`` (fixing quirk Q11).

    ``trg_max_len`` (default: ``max_len``) pads the target stream to a
    different fixed length — sequence-parallel training sets it to
    ``max_len + 1`` so the teacher-forced decoder input (``trg[:, :-1]``,
    one shorter) has length ``max_len`` and divides the ring's seq axis.
    """
    src_texts = [s for s, _ in pairs]
    trg_texts = [t for _, t in pairs]

    def mk(texts, length):
        return TextPipeline.fit(
            texts,
            tokenizer,
            # Truncate runs after the sos prepend, so length-1 keeps sos + up
            # to length-2 content tokens, and the eos append lands within
            # length — the reference's Truncate(199)+Pad(200) capacity.
            max_seq_len=length - 1,
            fixed_len=length,
            **kwargs,
        )

    return (
        mk(src_texts, max_len),
        mk(trg_texts, max_len if trg_max_len is None else trg_max_len),
    )
