from machine_learning_apache_spark_tpu.data.frame import ArrayFrame
from machine_learning_apache_spark_tpu.data.libsvm import read_libsvm, write_libsvm
from machine_learning_apache_spark_tpu.data.reader import DataReader

__all__ = ["ArrayFrame", "read_libsvm", "write_libsvm", "DataReader"]
