"""data — ingestion (libsvm/image/text), sampling, loading (reference L1-L3)."""

from machine_learning_apache_spark_tpu.data.frame import ArrayFrame
from machine_learning_apache_spark_tpu.data.libsvm import read_libsvm, write_libsvm
from machine_learning_apache_spark_tpu.data.reader import DataReader
from machine_learning_apache_spark_tpu.data.sampler import DistributedSampler
from machine_learning_apache_spark_tpu.data.loader import (
    ArrayDataset,
    DataLoader,
    random_split,
)
from machine_learning_apache_spark_tpu.data.bucketing import (
    BucketByLengthLoader,
    assign_buckets,
)
from machine_learning_apache_spark_tpu.data.text import (
    PAD_ID,
    SOS_ID,
    EOS_ID,
    UNK_ID,
    TextPipeline,
    Vocab,
    classification_pipeline,
    get_tokenizer,
    register_tokenizer,
    translation_pipelines,
)
from machine_learning_apache_spark_tpu.data.datasets import (
    load_ag_news,
    load_fashion_mnist,
    load_multi30k,
    synthetic_image_classification,
    synthetic_text_classification,
    synthetic_translation_pairs,
)

__all__ = [
    "ArrayFrame",
    "read_libsvm",
    "write_libsvm",
    "DataReader",
    "DistributedSampler",
    "ArrayDataset",
    "DataLoader",
    "random_split",
    "load_ag_news",
    "load_fashion_mnist",
    "load_multi30k",
    "synthetic_image_classification",
    "synthetic_text_classification",
    "synthetic_translation_pairs",
    "PAD_ID",
    "SOS_ID",
    "EOS_ID",
    "UNK_ID",
    "TextPipeline",
    "Vocab",
    "BucketByLengthLoader",
    "assign_buckets",
    "classification_pipeline",
    "get_tokenizer",
    "register_tokenizer",
    "translation_pipelines",
]
