"""machine_learning_apache_spark_tpu — a TPU-native ML framework.

A brand-new JAX/XLA framework with the capabilities of the reference repo
``Makkan13/Machine_Learning---Apache-Spark`` (Spark-launched PyTorch training),
re-designed TPU-first:

- ``session``   — Spark-session-equivalent run configuration layer (reference L0,
  ``mllib_multilayer_perceptron_classifier.py:12-19``).
- ``data``      — ingestion: libsvm reader, image/text dataset loaders, distributed
  sampler, device-feeding loader (reference L1-L3).
- ``text``      — tokenizer / vocab / transform chains (reference C13).
- ``models``    — the model zoo: MLP, CNN, LSTM, encoder-decoder Transformer
  (reference C2/C5/C8/C14-C23) as reusable Flax modules.
- ``ops``       — attention core, masks, positional encodings, layer norm; Pallas
  kernels for the hot paths.
- ``parallel``  — mesh construction, data/tensor/sequence parallelism. The
  reference's DDP-over-gloo (C11) becomes ``lax.pmean`` of grads over the mesh
  axis ``"data"`` inside a compiled step.
- ``train``     — losses, metrics, train state, fit/evaluate loops, timing spans
  (reference L7, the loop machinery every script re-implements inline).
- ``launcher``  — the TorchDistributor equivalent (reference C12): spawn one
  process per host, rendezvous, run a function by reference, rank-0 result.
- ``mllib``     — L-BFGS MLP baseline trainer + evaluator (reference C1 parity).
- ``utils``     — prng, logging, checkpointing, profiling hooks.

The package directory name is the importable form of the project name
``machine_learning---apache-spark_tpu`` (dashes are not valid in Python
identifiers).
"""

__version__ = "0.1.0"

import os as _os

# Backend override via the config API, applied at first package import —
# the ``spark.master local`` analogue. On images whose sitecustomize
# pre-registers an accelerator plugin, the JAX_PLATFORMS *env var* can be
# ineffective (or leave a process pointed at a dead tunnel that hangs at
# backend init); ``jax.config.update`` before the first backend touch is
# the reliable lever, so expose it as one:
#
#   MLSPARK_PLATFORM=cpu MLSPARK_CPU_DEVICES=8 python examples/cnn.py
#
# No-ops (with a warning) if the backend was already initialized.
#
# Direct reads by design: this block must run before the first jax import
# settles a platform, and utils.env sits in the jax-importing utils package.
# Both names ARE registered; only the accessor differs.
# mlspark-lint: ok env-direct-read -- pre-platform bootstrap, see comment above
if _os.environ.get("MLSPARK_PLATFORM") or _os.environ.get("MLSPARK_CPU_DEVICES"):
    import jax as _jax

    # jax.config.update("jax_platforms", ...) succeeds SILENTLY with no
    # effect once a backend is initialized (no after-init validator in
    # jax), so the staleness check must be explicit or the override
    # silently no-ops — the exact misconfiguration this knob exists to
    # surface.
    try:
        from jax._src import xla_bridge as _xb

        _too_late = _xb.backends_are_initialized()
    except Exception:
        _too_late = False
    if _too_late:
        import warnings as _warnings

        _warnings.warn(
            "MLSPARK_PLATFORM/MLSPARK_CPU_DEVICES set but the JAX backend "
            "was already initialized; the override had no effect",
            stacklevel=2,
        )
    else:
        if _os.environ.get("MLSPARK_PLATFORM"):  # mlspark-lint: ok env-direct-read -- pre-platform bootstrap, see top of block
            _jax.config.update("jax_platforms", _os.environ["MLSPARK_PLATFORM"])  # mlspark-lint: ok env-direct-read -- pre-platform bootstrap
        if _os.environ.get("MLSPARK_CPU_DEVICES"):  # mlspark-lint: ok env-direct-read -- pre-platform bootstrap, see top of block
            from machine_learning_apache_spark_tpu.utils.jax_compat import (
                set_num_cpu_devices as _set_num_cpu_devices,
            )

            _set_num_cpu_devices(int(_os.environ["MLSPARK_CPU_DEVICES"]))  # mlspark-lint: ok env-direct-read -- pre-platform bootstrap

from machine_learning_apache_spark_tpu.session import Session, SessionBuilder

__all__ = ["Session", "SessionBuilder", "__version__"]
