"""``mlspark-submit`` — the spark-submit analogue (reference L0, submit mode).

The reference's distributed scripts build their session from an EMPTY conf and
rely on ``spark-submit`` to inject resources, then read
``spark.executor.instances`` back as the world size
(``distributed_cnn.py:41-43``; SURVEY.md §1 L0 "spark-submit config" mode).
This CLI is that injection point for the TPU framework:

    python -m machine_learning_apache_spark_tpu.submit \
        --conf spark.executor.instances=4 examples/distributed_cnn.py

Mechanism: every ``--conf`` key is normalized onto the ``MLSPARK_*`` env
contract that ``SessionConfig.from_env`` already reads (``config.py``), and
the driver script runs once in a child interpreter with that environment —
exactly spark-submit's division of labor: the submitter owns resources, the
script's empty ``Session.builder`` reads them back, and any gang spawning
happens inside via the Distributor (C12).

Multi-host rendezvous flags (``--coordinator``, ``--num-processes``,
``--process-id``) map onto the MASTER_ADDR/WORLD_SIZE/RANK analogues
(``distributed_cnn.py:22-27`` commented block; SURVEY.md §2.4) so one
``mlspark-submit`` per host also covers the torchrun-style launch.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _conf_to_env(key: str, value: str) -> tuple[str, str]:
    """``spark.executor.instances`` / ``executor_instances`` →
    ``MLSPARK_EXECUTOR_INSTANCES`` (the ``SessionConfig.from_env`` contract)."""
    norm = key.strip()
    if norm.startswith("spark."):
        norm = norm[len("spark."):]
    norm = norm.replace(".", "_").upper()
    return f"MLSPARK_{norm}", value


def build_env(ns: argparse.Namespace) -> dict[str, str]:
    env = dict(os.environ)
    for item in ns.conf or []:
        if "=" not in item:
            raise SystemExit(f"--conf expects key=value, got {item!r}")
        key, _, value = item.partition("=")
        ek, ev = _conf_to_env(key, value)
        env[ek] = ev
    if ns.name:
        env["MLSPARK_APP_NAME"] = ns.name
    if ns.platform:
        env["MLSPARK_PLATFORM"] = ns.platform
    if ns.coordinator:
        env["MLSPARK_COORDINATOR"] = ns.coordinator
    if ns.num_processes is not None:
        env["MLSPARK_NUM_PROCESSES"] = str(ns.num_processes)
        # the conf-derived world size the reference reads back (:43)
        env.setdefault("MLSPARK_EXECUTOR_INSTANCES", str(ns.num_processes))
    if ns.process_id is not None:
        env["MLSPARK_PROCESS_ID"] = str(ns.process_id)
    return env


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="mlspark-submit",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--conf", action="append", metavar="KEY=VALUE",
        help="session conf entry; spark.* keys are accepted and normalized",
    )
    parser.add_argument("--name", help="application name")
    parser.add_argument(
        "--platform", help="force a JAX platform for the run (tpu/cpu)"
    )
    parser.add_argument(
        "--coordinator", help="host:port rendezvous (multi-host runs)"
    )
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("script", help="driver script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(argv)

    if not os.path.exists(ns.script):
        raise SystemExit(f"script not found: {ns.script}")
    env = build_env(ns)
    # A child interpreter (not runpy in-process): the submitter may itself
    # have touched a JAX backend, and platform/conf choices must reach the
    # driver before ITS first backend init.
    cmd = [sys.executable, ns.script, *ns.script_args]
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    sys.exit(main())
