"""Loss functions.

The reference uses ``nn.CrossEntropyLoss`` everywhere; the MT driver uses the
per-token variant with ``ignore_index=0, reduction='none'`` followed by a
manual pad-masked mean (``pytorch_machine_translator.py:125-126,182-188``).
Both shapes live here, once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, *, reduction: str = "mean"
) -> jnp.ndarray:
    """Softmax cross-entropy over integer labels — ``nn.CrossEntropyLoss``
    semantics (``pytorch_cnn.py:108``): ``reduction="mean"`` (default) or
    ``"none"`` for per-example losses (weighted-mean callers)."""
    per_example = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if reduction == "none":
        return per_example
    if reduction != "mean":
        raise ValueError(f"unknown reduction {reduction!r}")
    return per_example.mean()


def masked_token_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    pad_id: int = 0,
) -> jnp.ndarray:
    """Pad-masked per-token CE: per-token losses where ``label != pad_id``,
    averaged over real tokens only — the MT driver's
    ``ignore_index=0, reduction='none'`` + manual mask-mean
    (``pytorch_machine_translator.py:182-188``).

    ``logits``: [..., S, V]; ``labels``: [..., S].
    """
    per_token = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    mask = (labels != pad_id).astype(per_token.dtype)
    total = jnp.sum(per_token * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def l2_regularization(params, scale: float) -> jnp.ndarray:
    leaves = jax.tree.leaves(params)
    return scale * sum(jnp.sum(jnp.square(p)) for p in leaves)
