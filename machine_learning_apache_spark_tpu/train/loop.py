"""Training/eval loop machinery — reference layer L7, implemented once.

Every reference script re-implements the same loop inline (SURVEY.md §1 L7):
epochs × batches of {forward → loss → zero_grad → backward → step}, then an
eval pass of softmax→argmax→accuracy, with wall-clock prints. Here the loop
body is a single jitted function (forward+backward+update fused into one XLA
program) and the Python loop only feeds batches and accumulates metrics.

Data parallelism needs no separate loop: with params replicated and the batch
sharded over the mesh's ``"data"`` axis, XLA's sharding propagation compiles
the gradient reduction into a ``psum`` over ICI — the reference's entire
DDP/gloo layer (C11) disappears into the compiled step (SURVEY.md §7).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.parallel.mesh import shard_batch
from machine_learning_apache_spark_tpu.train.metrics import MetricBundle, logits_accuracy
from machine_learning_apache_spark_tpu.train.state import TrainState
from machine_learning_apache_spark_tpu.utils.logging import get_logger
from machine_learning_apache_spark_tpu.utils.timing import Timer

log = get_logger(__name__)

# loss_fn contract: (params, batch, rng) -> (scalar_loss, aux_dict)
LossFn = Callable[[Any, Any, jax.Array], tuple[jnp.ndarray, dict]]


def make_train_step(loss_fn: LossFn):
    """One fused forward+backward+update XLA program.

    The incoming state is donated: params/opt-state buffers are updated in
    place instead of copied — on TPU that halves the optimizer's HBM
    traffic, typically the bound on small models. Callers must rebind
    (``state = step(state, ...)``), which ``fit`` does.
    """

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state: TrainState, batch, rng: jax.Array):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, rng
        )
        return state.apply_gradients(grads), loss, aux

    return step


def make_multi_step(loss_fn: LossFn):
    """K fused train steps per host dispatch, scanned inside ONE XLA program.

    Why this exists: every ``step(...)`` call costs a host dispatch (an RPC
    round-trip on tunneled/remote device topologies — measured ~2.3 ms/step
    against a 0.65 ms device step for the TinyVGG workload, i.e. the host
    caps a small model at ~30% of the chip). ``lax.scan`` moves the step
    loop into the compiled program: one dispatch covers K steps, the device
    runs back-to-back, and the host has K step-times to enqueue the next
    call. The K microbatches arrive stacked on a leading axis
    (``parallel.shard_batch_stack``); K is implicit in the shapes.

    Rng contract: the body splits exactly like ``fit``'s host loop
    (``rng, step_rng = split(rng)`` per step) and the advanced key is
    returned, so a run produces bit-identical params whether dispatched
    one step at a time or K at a time (pinned by
    ``tests/test_train.py::TestStepsPerCall``).
    """

    @functools.partial(jax.jit, donate_argnums=0)
    def multi_step(state: TrainState, batches, rng: jax.Array):
        def body(carry, batch):
            state, rng = carry
            rng, step_rng = jax.random.split(rng)
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch, step_rng
            )
            return (state.apply_gradients(grads), rng), (loss, aux)

        (state, rng), (losses, auxes) = jax.lax.scan(body, (state, rng), batches)
        return state, rng, losses, auxes

    return multi_step


def make_eval_step(loss_fn: LossFn):
    @jax.jit
    # mlspark-lint: ok jit-donate -- eval step: state is read, not updated; donating would consume the caller's buffers
    def step(state: TrainState, batch, rng: jax.Array):
        return loss_fn(state.params, batch, rng)

    return step


@dataclass
class FitResult:
    state: TrainState
    train_seconds: float
    history: list[dict] = field(default_factory=list)
    # Step the run auto-resumed from (fit(resume=True) found a valid
    # checkpoint); None for a fresh run.
    resumed_step: int | None = None

    @property
    def final_loss(self) -> float:
        return self.history[-1]["loss"] if self.history else float("nan")


def _rng_to_meta(rng: jax.Array) -> list[int]:
    """Host-serializable form of a PRNG key for the checkpoint sidecar."""
    import numpy as np

    return np.asarray(jax.device_get(jax.random.key_data(rng))).tolist()


def _rng_from_meta(data: list[int]) -> jax.Array:
    return jax.random.wrap_key_data(jnp.asarray(data, dtype=jnp.uint32))


def _with_comms_counters(zstep, state):
    """Wrap the fused ZeRO-1 step with the comms telemetry contract
    (docs/OBSERVABILITY.md): per-step wire-byte counters (static amounts —
    no device sync), a per-chip optimizer-state-bytes gauge set once, and
    one ``counter`` event per fit so the gang report
    (``telemetry_report.py`` comms section) can compute bytes/step."""
    if not telemetry.enabled():
        return zstep
    from machine_learning_apache_spark_tpu.parallel import zero as _zero

    stats = zstep.comms_stats
    reg = telemetry.get_registry()
    reg.gauge("comms", "opt_state_bytes_per_chip").set(
        _zero.opt_state_bytes_per_chip(state)
    )
    telemetry.annotate(
        "comms.zero1",
        **{k: v for k, v in stats.items() if k != "grad_bytes_fp32"},
    )
    rs = reg.counter("comms", "bytes_reduce_scattered")
    ag = reg.counter("comms", "bytes_allgathered")
    exposed = reg.counter("comms", "bytes_exposed")
    overlapped = reg.counter("comms", "bytes_overlapped")
    counted = [0]

    def step(st, batch, rng):
        out = zstep(st, batch, rng)
        rs.inc(stats["reduce_scatter_bytes"])
        ag.inc(stats["allgather_bytes"])
        exposed.inc(stats["bytes_exposed"])
        overlapped.inc(stats["bytes_overlapped"])
        counted[0] += 1
        return out

    def flush():
        if not counted[0]:
            return
        log_ = telemetry.get_log()
        common = {
            "steps": counted[0],
            "comms_dtype": stats["comms_dtype"],
            "overlap": stats["overlap"],
        }
        log_.emit(
            "counter", "comms.bytes_reduce_scattered",
            value=counted[0] * stats["reduce_scatter_bytes"], attrs=common,
        )
        log_.emit(
            "counter", "comms.bytes_allgathered",
            value=counted[0] * stats["allgather_bytes"], attrs=common,
        )
        # The exposed/overlapped split of the same wire bytes — the static
        # pipeline model from comms_bytes_per_step (overlap on: 1/nb of
        # each collective exposed at the pipeline fill/drain; off: all of
        # it). telemetry_report's comms section turns these into the
        # comms-bound/compute-bound verdict inputs.
        log_.emit(
            "counter", "comms.bytes_exposed",
            value=counted[0] * stats["bytes_exposed"], attrs=common,
        )
        log_.emit(
            "counter", "comms.bytes_overlapped",
            value=counted[0] * stats["bytes_overlapped"], attrs=common,
        )
        counted[0] = 0

    step.flush_comms = flush
    return step


def fit(
    state: TrainState,
    loss_fn: LossFn,
    train_loader: Iterable | None = None,
    *,
    data: Iterable | None = None,
    epochs: int,
    rng: jax.Array | None = None,
    mesh=None,
    log_every: int = 100,
    emit: Callable[[str], None] | None = None,
    checkpointer=None,
    checkpoint_every: int = 1,
    profile_dir: str | None = None,
    profile_window: tuple[int, int] = (2, 5),
    metrics_file: str | None = None,
    sync_check_every: int = 0,
    zero1: bool = False,
    dp_mode: str | None = None,
    dp_bucket_bytes: int | None = None,
    dp_comms_dtype: str | None = None,
    dp_overlap: bool | None = None,
    steps_per_call: int = 1,
    prefetch_to_device: int = 0,
    resume: bool = False,
    elastic: bool | None = None,
) -> FitResult:
    """The canonical loop (``pytorch_cnn.py:125-146`` shape): epochs × batches,
    per-``log_every``-batch loss/time prints
    (``pytorch_machine_translator.py:199-205``), total wall-time at the end
    (the universal reference metric, SURVEY.md §6).

    ``train_loader`` yields batch pytrees; if it has ``set_epoch``, it is
    called per epoch (the ``sampler.set_epoch`` contract,
    ``distributed_cnn.py:168``, with correct Q3 semantics).

    ``data=`` is an alias for ``train_loader`` and the idiomatic spelling
    for an ``ingest.StreamingPipeline``: fit binds its mesh to the
    pipeline's device stage, consumes device-resident batches directly,
    captures the pipeline's stream state (mixture RNG, cursors) in each
    checkpoint's meta sidecar, restores it on ``resume=True`` so the
    resumed run replays the identical batch sequence, and shuts the
    pipeline's producer threads down when fit returns OR raises (no
    leaked threads — docs/DATA.md). The scanned ``steps_per_call`` path
    and fit's own ``prefetch_to_device`` stack/shard host batches
    themselves, so with either of those the pipeline is bound to yield
    host batches.

    ``checkpointer`` (a ``train.checkpoint.CheckpointManager``) saves the
    state every ``checkpoint_every`` epochs — persistence the reference
    lacks entirely (SURVEY.md §5 checkpoint/resume).

    ``profile_dir`` captures a jax.profiler device trace over the global-step
    window ``profile_window`` (skipping compile/warmup steps) — the tracing
    subsystem the reference approximates with ``time.time()`` pairs
    (SURVEY.md §5).

    ``metrics_file`` appends one JSON line per epoch (and a final run
    record) — the structured counterpart of the reference's print-only
    metrics (SURVEY.md §5 metrics/logging).

    ``sync_check_every=N`` runs ``parallel.assert_replicas_in_sync`` on the
    params every N epochs — the race-detector analogue for the reference's
    Q2-class replica-drift bug (SURVEY.md §5), raising if a multi-process
    gang's replicas diverge. 0 (default) disables the check (it is a
    cross-host sync point).

    ``dp_mode="zero1"`` (or env ``MLSPARK_DP_MODE=zero1`` — the launcher
    gang plumbing) switches the data-parallel update to the fused ZeRO-1
    step (``parallel.zero``): gradients reduce-scatter over the ``data``
    axis, each chip updates its 1/N parameter shard (optimizer moments
    sharded from the start — ~1/N the optimizer memory), updated params
    allgather back. Same trajectory as the replicated step (bit-identical
    with the default fp32 comms). ``dp_bucket_bytes`` /
    ``dp_comms_dtype`` (env ``MLSPARK_ZERO1_BUCKET_BYTES`` /
    ``MLSPARK_COMMS_DTYPE``) tune the gradient collective;
    ``dp_overlap`` (env ``MLSPARK_ZERO1_OVERLAP``, default on) selects
    the pipelined bucket schedule that hides the reduce-scatter behind
    backward and the params allgather behind the per-bucket optimizer
    updates — see docs/PARALLELISM.md for the tradeoffs. On a hybrid
    ``data x model`` mesh (``parallel.make_mesh({"data": D, "model":
    T})``) the ZeRO-1 update composes with tensor parallelism: params
    keep their logical TP placement, the flat optimizer moments shard
    over all D x T devices, and the step runs the implicit
    weight-update-sharding form (fp32 comms only). Distinct from the
    legacy ``zero1=True`` flag (implicit opt-state sharding, replicated
    step).

    ``steps_per_call=K`` dispatches K batches per host→device call via a
    ``lax.scan``-fused step (``make_multi_step``) — same math, same rng
    stream, K× fewer dispatches; the win for small/fast models whose step
    time is comparable to dispatch overhead. Ragged trailing groups (end of
    epoch) fall back to single steps, so any loader length works.

    ``prefetch_to_device=N`` (with a mesh, single-step path) shards batches
    onto the mesh N ahead of consumption (``parallel.device_prefetch``):
    host→device transfers overlap device compute instead of serializing in
    front of each dispatch. Combine with the loader's host-side
    ``prefetch`` for a fully double-buffered input pipeline.

    ``resume=True`` (with a ``checkpointer``) restores the newest valid
    checkpoint before training and continues the run from it: params and
    opt-state from the checkpoint, epoch counter and rng stream from its
    sidecar meta (docs/FAULT_TOLERANCE.md). The epoch loop then runs only
    the remaining epochs and the rng stream picks up exactly where the
    interrupted run left it, so a resumed trajectory is bit-identical to
    an uninterrupted one from the last checkpoint onward. No checkpoint
    on disk -> a normal fresh run; ``FitResult.resumed_step`` records
    which happened.

    Every checkpoint sidecar carries a topology stamp (world size, mesh
    axes, dp mode, ZeRO-1 bucket layout). A resume whose own topology
    matches restores bit-identically as above; on mismatch, ``elastic``
    decides (arg > ``MLSPARK_ELASTIC`` env — set by
    ``Distributor(elastic=True)`` — > off): disabled raises
    ``TopologyMismatch`` naming both topologies (a wrong-world resume
    must never silently misload per-rank shards); enabled routes the
    restore through ``train/reshard.py`` — the old gang's per-rank flat
    optimizer shards are reassembled and resharded onto this run's mesh,
    params/rng/epoch adopt, and the ingest stream state is re-scattered
    (equalization recomputes for the new shard count). See
    docs/FAULT_TOLERANCE.md "Elastic resume".

    The input ``state``'s buffers are CONSUMED (the fused step donates them
    for in-place updates); use ``FitResult.state``, never the argument,
    afterwards. Build from copied params if two fits must share an init.
    """
    from machine_learning_apache_spark_tpu.utils.profiling import StepWindowTracer
    from machine_learning_apache_spark_tpu.parallel import zero as _zero

    if data is not None:
        if train_loader is not None:
            raise ValueError("pass either train_loader or data=, not both")
        train_loader = data
    if train_loader is None:
        raise ValueError("fit needs a train_loader (or data=...)")
    emit = emit or log.info
    rng = rng if rng is not None else jax.random.key(0)
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
    # Streaming-pipeline integration (duck-typed marker, no import cycle):
    # bind fit's mesh into the pipeline's device stage — except on the
    # host-batch paths (scan stacking, fit-side device prefetch), which
    # place batches themselves.
    streaming = getattr(train_loader, "is_streaming_pipeline", False)
    if streaming:
        if mesh is not None:
            train_loader.bind(mesh=mesh)
        if steps_per_call > 1 or (prefetch_to_device > 0 and mesh is not None):
            train_loader.bind(device=False)
    mode = _zero.resolve_dp_mode(dp_mode)
    if mode == "zero1":
        # The fused sharded-update path (parallel.zero,
        # docs/PARALLELISM.md): reduce-scatter grads, update this chip's
        # 1/N param shard, allgather. Distinct from the legacy
        # zero1=True flag, which shards the optimizer moments via XLA
        # propagation but keeps the replicated allreduce step.
        if mesh is None:
            raise ValueError("dp_mode='zero1' requires a mesh (use_mesh=True)")
        if zero1:
            raise ValueError(
                "pass either dp_mode='zero1' (fused reduce-scatter step) or "
                "zero1=True (implicit opt-state sharding), not both"
            )
        if steps_per_call > 1:
            raise ValueError(
                "dp_mode='zero1' runs its own fused step; steps_per_call "
                "fusion is not supported with it"
            )
    elif (
        dp_bucket_bytes is not None
        or dp_comms_dtype is not None
        or dp_overlap is not None
    ):
        raise ValueError(
            "dp_bucket_bytes/dp_comms_dtype/dp_overlap only apply to "
            "dp_mode='zero1'"
        )
    step_fn = make_train_step(loss_fn)
    multi_fn = make_multi_step(loss_fn) if steps_per_call > 1 else None
    tracer = StepWindowTracer(
        profile_dir, start=profile_window[0], stop=profile_window[1]
    )
    if mesh is not None and mode == "zero1":
        config = _zero.Zero1Config.from_env(
            bucket_bytes=dp_bucket_bytes,
            comms_dtype=dp_comms_dtype,
            overlap=dp_overlap,
        )
        state = _zero.shard_optimizer_state(state, mesh, config)
        step_fn = _with_comms_counters(
            _zero.make_zero1_step(loss_fn, mesh, state), state
        )
    elif mesh is not None:
        # Logical-annotation-aware placement: DP-only meshes replicate (DDP
        # whole-replica semantics); a mesh with a "model" axis tensor-shards
        # annotated params and their optimizer moments (SURVEY.md §2.3).
        # zero1=True additionally shards optimizer moments 1/N over the
        # "data" axis (ZeRO stage 1) — identical math, less HBM per chip.
        from machine_learning_apache_spark_tpu.parallel.tensor_parallel import (
            shard_state,
        )

        state = shard_state(state, mesh, zero1=zero1)
    elif zero1:
        # Never a silent no-op (same convention as the recipe-surface
        # parallelism flags): without a mesh there is nothing to shard
        # the optimizer moments over.
        raise ValueError("zero1=True requires a mesh (use_mesh=True)")

    resumed_step: int | None = None
    resume_meta: dict = {}
    start_epoch = 0
    if resume and checkpointer is not None:
        from machine_learning_apache_spark_tpu.train import (
            checkpoint as _ckpt,
            reshard as _reshard,
        )

        # After shard_state so the restore template carries the run's real
        # layout — orbax restores straight into the sharded buffers.
        # Topology is validated BEFORE any restore: a cross-topology
        # attempt would fail shapes-first (or worse, misload), so the
        # stamp decides the route up front.
        current = _ckpt.topology_stamp(state)
        old = checkpointer.newest_topology_stamp()
        crossed = old is not None and not _ckpt.same_topology(old, current)
        if crossed:
            if not _reshard.resolve_elastic(elastic):
                raise _reshard.TopologyMismatch(
                    f"checkpoints under {checkpointer.directory} were "
                    f"written by a different topology — checkpoint "
                    f"topology {old} vs this run's {current}. Pass "
                    "elastic=True (or set MLSPARK_ELASTIC=1, which "
                    "Distributor(elastic=True) does) to reshard, or "
                    "point the run at a fresh checkpoint directory."
                )
            restored = _reshard.elastic_restore(
                checkpointer, state, old_stamp=old
            )
        else:
            restored = checkpointer.restore_latest_valid(state)
        if restored is not None:
            state, resumed_step, resume_meta = restored
            if "rng" in resume_meta:
                rng = _rng_from_meta(resume_meta["rng"])
            start_epoch = int(resume_meta.get("epoch", -1)) + 1
            if streaming and resume_meta.get("ingest") is not None:
                # Stream position (mixture RNG state, per-source cursors)
                # from the sidecar: the resumed run replays the exact
                # batch sequence the interrupted one would have produced.
                ingest_state = resume_meta["ingest"]
                if crossed:
                    from machine_learning_apache_spark_tpu.ingest import (
                        rescatter_stream_state,
                    )

                    ingest_state = rescatter_stream_state(
                        ingest_state,
                        old_world=int(old.get("world_size", 1)),
                        new_world=int(current.get("world_size", 1)),
                        shard=getattr(train_loader, "shard", "records"),
                    )
                train_loader.load_state_dict(ingest_state)
            if crossed:
                telemetry.annotate(
                    "train.elastic_resume",
                    step=int(resumed_step),
                    old_world=int(old.get("world_size", 1)),
                    new_world=int(current.get("world_size", 1)),
                    old_mesh=old.get("mesh"),
                    new_mesh=current.get("mesh"),
                    dp_mode=current.get("dp_mode"),
                )
                emit(
                    f"elastic resume: resharded checkpoint step "
                    f"{resumed_step} from world "
                    f"{old.get('world_size')} onto world "
                    f"{current.get('world_size')}"
                )
            emit(
                f"resuming from checkpoint step {resumed_step} "
                f"(starting epoch {start_epoch})"
            )

    from machine_learning_apache_spark_tpu.train.metrics import MetricsLogger

    # Rank-0 gated like every other metrics emission (utils.logging): a
    # multi-process gang writing one shared file would duplicate every record.
    sink = (
        MetricsLogger(metrics_file)
        if metrics_file and jax.process_index() == 0
        else None
    )
    total_timer = Timer("train").start()
    span_timer = Timer("span").start()
    fit_span = telemetry.span(
        "train.fit", epochs=epochs, steps_per_call=steps_per_call,
        resumed_step=resumed_step,
    )
    try:
        try:
            with fit_span:
                state, history = _run_epochs(
                    state, step_fn, train_loader, epochs, rng, mesh,
                    log_every, emit, tracer, checkpointer, checkpoint_every,
                    span_timer, sink, sync_check_every, multi_fn,
                    steps_per_call, prefetch_to_device, start_epoch,
                    int(resumed_step) if resumed_step is not None else 0,
                )
        except BaseException as e:
            # Flight recorder: an unhandled exception out of the training
            # loop ships with its last events (the failing step's spans are
            # the newest entries). Errored span_end for train.fit was just
            # emitted by the with-block, so it is included.
            telemetry.dump_flight(
                f"train.fit:{type(e).__name__}", extra={"error": str(e)[:500]}
            )
            raise
        finally:
            # An exception mid-window must still stop the (process-global)
            # jax profiler, or every later trace in this process fails to
            # start.
            tracer.close()
            # Comms byte totals land on the event log even for a run that
            # died mid-epoch (the flight recorder then carries them too).
            if hasattr(step_fn, "flush_comms"):
                step_fn.flush_comms()
        if not history and resume_meta.get("metrics"):
            # Already-complete resume (a gang retry where THIS rank had
            # finished before teardown): zero epochs remain, so report the
            # final epoch's metrics recorded in the checkpoint sidecar —
            # the caller's loss-parity checks must hold on every retried
            # rank, including the ones with nothing left to do.
            history = [dict(resume_meta["metrics"])]
        # Block on the final state so the reported wall-time includes device
        # work (the reference's time.time() pairs measure eager CPU
        # execution; under async dispatch the analogue requires a sync point).
        jax.block_until_ready(state.params)
        seconds = total_timer.stop()
        if checkpointer is not None:
            checkpointer.wait()  # durability barrier, outside the timed span
        if sink is not None:
            sink.write({
                "kind": "run",
                "train_seconds": seconds,
                "epochs": len(history),
                "final_loss": history[-1].get("loss") if history else None,
            })
    finally:
        if sink is not None:
            sink.close()
        if streaming:
            # Producer-thread teardown on BOTH exits (return and raise):
            # a crashed fit must not leave ingest threads pinning buffered
            # batches (pinned by tests/test_ingest.py).
            train_loader.shutdown()
    emit(f"Training Time: {seconds:.3f} sec")
    return FitResult(
        state=state, train_seconds=seconds, history=history,
        resumed_step=resumed_step,
    )


def _run_epochs(
    state, step_fn, train_loader, epochs, rng, mesh, log_every, emit,
    tracer, checkpointer, checkpoint_every, span_timer, sink=None,
    sync_check_every=0, multi_fn=None, steps_per_call=1,
    prefetch_to_device=0, start_epoch=0, start_step=0,
):
    from machine_learning_apache_spark_tpu.parallel.mesh import (
        device_prefetch,
        shard_batch_stack,
    )
    from machine_learning_apache_spark_tpu.utils.faults import maybe_fault

    # Device prefetch applies to the single-step path: sharded transfers
    # are issued N batches ahead so they overlap compute. The scanned path
    # stacks its own groups (and one dispatch already buys K step-times of
    # host slack), so it keeps raw batches.
    use_prefetch = (
        prefetch_to_device > 0 and mesh is not None and multi_fn is None
    )
    # A streaming pipeline with an active device stage delivers batches
    # already placed (device_put, or mesh-sharded when fit bound a mesh);
    # the single-step path must not re-shard them.
    pipeline_device = getattr(train_loader, "yields_device_batches", False)

    history: list[dict] = []
    # On resume the step counter continues from the restored checkpoint, so
    # step-pinned coordinates (profiler windows, injected faults, log lines)
    # mean the same thing in a resumed run as in an uninterrupted one.
    global_step = start_step
    last_emit_step = global_step
    for epoch in range(start_epoch, epochs):
        # Manual enter/exit (not a with-block) keeps the 130-line epoch body
        # at its indent. On an exception the span_end is skipped — the step
        # span and fit span still close errored, and _Span.__exit__ pops
        # leaked ids, so parent attribution stays correct.
        epoch_span = telemetry.span("train.epoch", epoch=epoch)
        epoch_span.__enter__()
        # Refresh the liveness beacon once per epoch: heartbeat payloads
        # and /healthz report phase + step without touching the hot loop.
        telemetry.beacon_update(
            phase="train", epoch=epoch, step=global_step
        )
        if hasattr(train_loader, "set_epoch"):
            train_loader.set_epoch(epoch)
        epoch_metrics = MetricBundle()
        # Step outputs stay on-device until a log point — float()ing per step
        # would sync the host into every step and serialize async dispatch.
        # Entries are (mean_loss, mean_aux, n_steps): n_steps > 1 for a
        # scanned multi-step dispatch, keeping epoch means weight-exact.
        pending: list[tuple] = []

        def _drain():
            for dev_loss, dev_aux, n in jax.device_get(pending):
                epoch_metrics.mean("loss").update(dev_loss, n)
                for k, v in dev_aux.items():
                    epoch_metrics.mean(k).update(v, n)
            pending.clear()

        def _log_point(prev_step):
            # Stride-aware: emit when the counter crossed a log_every
            # boundary this dispatch (multi-step strides can jump past the
            # exact multiple).
            return log_every and (
                global_step // log_every > prev_step // log_every
            )

        def _emit_log():
            # The lap spans however many batches actually ran since the last
            # emit — with a multi-step stride that need not equal log_every
            # (one K-step dispatch can cross several boundaries), so report
            # the real count.
            nonlocal last_emit_step
            covered = global_step - last_emit_step
            last_emit_step = global_step
            # Log cadence doubles as beacon cadence: step stays fresh on
            # /healthz and in heartbeat payloads at zero hot-loop cost.
            telemetry.beacon_update(phase="train", step=global_step)
            _drain()
            emit(
                f"epoch {epoch} step {global_step} | "
                f"{epoch_metrics.log_line()} | {span_timer.lap():.3f} sec/{covered} batches"
            )

        group: list = []

        def _flush_group():
            nonlocal state, rng, global_step
            stacked = (
                shard_batch_stack(mesh, group)
                if mesh is not None
                else jax.tree.map(lambda *xs: jnp.stack(xs), *group)
            )
            tracer.on_step(global_step)
            prev = global_step
            with telemetry.span(
                "train.step_group", start=prev, count=len(group)
            ):
                # The scanned dispatch covers steps [prev, prev+K): check
                # every coordinate in the span so a step-pinned fault fires
                # regardless of steps_per_call (at group granularity — the
                # whole group is lost, which is within the
                # <=1-checkpoint-interval guarantee).
                for s in range(prev, prev + len(group)):
                    maybe_fault("train_step", step=s)
                state, rng, losses, auxes = multi_fn(state, stacked, rng)
            global_step += len(group)
            pending.append((
                losses.mean(),
                jax.tree.map(lambda v: v.mean(), auxes),
                len(group),
            ))
            group.clear()
            if _log_point(prev):
                _emit_log()

        def _single_step(batch, presharded=False):
            nonlocal state, rng, global_step
            if mesh is not None and not presharded:
                batch = shard_batch(mesh, batch)
            rng, step_rng = jax.random.split(rng)
            tracer.on_step(global_step)
            with telemetry.span("train.step", step=global_step):
                maybe_fault("train_step", step=global_step)
                state, loss, aux = step_fn(state, batch, step_rng)
            global_step += 1
            pending.append((loss, aux, 1))
            if _log_point(global_step - 1):
                _emit_log()

        epoch_iter = (
            device_prefetch(train_loader, mesh, depth=prefetch_to_device)
            if use_prefetch
            else train_loader
        )
        for batch in epoch_iter:
            if multi_fn is not None:
                group.append(batch)
                if len(group) == steps_per_call:
                    _flush_group()
            else:
                _single_step(batch, presharded=use_prefetch or pipeline_device)
        # Ragged trailing group: fewer than steps_per_call batches left in
        # the epoch — run them as single steps (a scan over a shorter stack
        # would force a recompile per distinct remainder length).
        for batch in group:
            _single_step(batch)
        group.clear()
        _drain()
        computed = epoch_metrics.compute()
        computed["epoch"] = epoch
        history.append(computed)
        if sink is not None:
            # state.step (not the run-local counter): stays consistent with
            # checkpoint labels across resumed runs.
            sink.write({"kind": "epoch", "step": int(state.step), **computed})
        if log_every:
            emit(f"epoch {epoch} done | {epoch_metrics.log_line()}")
        if sync_check_every and (epoch + 1) % sync_check_every == 0:
            # BEFORE the checkpoint save: a diverged state must raise here,
            # not get persisted as the latest resumable checkpoint first.
            from machine_learning_apache_spark_tpu.parallel import (
                assert_replicas_in_sync,
            )

            div = assert_replicas_in_sync(state.params)
            emit(f"epoch {epoch} replica divergence: {div:.3g}")
        if checkpointer is not None and (
            (epoch + 1) % max(checkpoint_every, 1) == 0 or epoch == epochs - 1
        ):
            # Async: orbax snapshots to host and writes in the background, so
            # checkpoint I/O never stalls device dispatch mid-training. The
            # sidecar meta carries the epoch counter and the post-epoch rng
            # key so fit(resume=True) continues the exact trajectory.
            meta = {
                "epoch": epoch,
                "rng": _rng_to_meta(rng),
                # JSON-safe copy of this epoch's metrics, so an
                # already-complete resume can still report them.
                "metrics": {
                    k: (v if isinstance(v, int) else float(v))
                    for k, v in computed.items()
                },
            }
            if getattr(train_loader, "is_streaming_pipeline", False):
                # Stream cursor + sampler RNG next to the rng key: the
                # epoch boundary is a quiescent point (the producer thread
                # has finished the epoch), so this capture is exact.
                meta["ingest"] = train_loader.state_dict()
            checkpointer.save(state, wait=False, meta=meta)
        epoch_span.__exit__(None, None, None)
    return state, history


def evaluate(
    state: TrainState,
    loss_fn: LossFn,
    eval_loader: Iterable,
    *,
    mesh=None,
    rng: jax.Array | None = None,
    emit: Callable[[str], None] | None = None,
) -> dict:
    """Eval pass: accumulated loss + metrics — the reference's
    ``model.eval()`` + ``no_grad`` + accuracy block
    (``pytorch_cnn.py:154-176``). Deterministic (loss_fn receives a fixed
    key; dropout layers must run deterministic under it).

    Consumes the WHOLE loader, matching the reference: a ragged tail batch
    (``drop_last=False`` loaders) that does not divide the mesh's data axis
    runs unsharded on the default device — one extra compile, zero skipped
    rows. Per-batch metrics are weighted by the real row count, and the
    total is returned as ``eval_samples`` so callers can assert full
    coverage. Exception: under a multi-process gang a ragged local tail
    cannot be assembled into a global array for the sharded step, so it is
    skipped with a warning (the single-controller boundary; every
    single-process path keeps full coverage).
    """
    from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS

    emit = emit or log.info
    rng = rng if rng is not None else jax.random.key(0)
    step_fn = make_eval_step(loss_fn)
    metrics = MetricBundle()
    # Divisibility is judged against the LOCAL device count: each process
    # contributes its local rows (shard_batch assembles the global array).
    local_size = (
        mesh.shape[DATA_AXIS] // jax.process_count() if mesh is not None else 1
    )
    total = 0
    for batch in eval_loader:
        n = len(jax.tree.leaves(batch)[0])
        if mesh is not None and n % local_size == 0:
            batch = shard_batch(mesh, batch)
        elif mesh is not None and jax.process_count() > 1:
            log.warning(
                "skipping %d-row ragged eval tail: a process-local tail "
                "cannot join the sharded step (%d local devices)",
                n, local_size,
            )
            continue
        loss, aux = step_fn(state, batch, rng)
        total += n
        metrics.mean("test_loss").update(loss, n)
        for k, v in aux.items():
            metrics.mean(k).update(v, n)
    out = metrics.compute()
    emit(" | ".join(f"{k}: {v:.5f}" for k, v in out.items()))
    out["eval_samples"] = total
    return out


def select_last_valid(
    logits: jnp.ndarray, tokens: jnp.ndarray, pad_id: int
) -> jnp.ndarray:
    """``[B, T, C]`` logits → ``[B, C]`` at each row's last non-pad
    position (all-pad rows fall back to position 0). Training loss and
    serving (``inference.Classifier``) MUST select through this one helper
    — scoring a different timestep than the loss trained silently degrades
    every deployed last-valid classifier."""
    idx = jnp.maximum((tokens != pad_id).sum(axis=-1) - 1, 0)
    return jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0, :]


def classification_loss(
    apply_fn, *, last_timestep: bool = False, train: bool = True,
    pad_id: int | None = None,
) -> LossFn:
    """Standard CE classification loss over ``(features, labels)`` batches.

    ``last_timestep=True`` selects ``logits[:, -1, :]`` — the LSTM recipe's
    last-position head (``pytorch_lstm.py:160``). With ``pad_id`` set, the
    selection becomes each row's last NON-PAD position instead of the fixed
    final column — the correct-semantics variant of the reference's
    last-position read, which on end-padded batches scores the hidden state
    after up to ``fixed_len − len(row)`` pad steps (state the recurrence
    must carry through constant inputs; a learning-speed tax the reference
    pays silently). ``train=True`` runs dropout (``model.train()``); pass
    ``train=False`` for the eval pass (``model.eval()`` + ``no_grad``,
    ``pytorch_cnn.py:154-176``).
    """
    from machine_learning_apache_spark_tpu.train.losses import cross_entropy

    def loss_fn(params, batch, rng):
        features, labels = batch
        logits = apply_fn(
            {"params": params},
            features,
            deterministic=not train,
            rngs={"dropout": rng} if train else None,
        )
        if last_timestep:
            if pad_id is not None:
                logits = select_last_valid(logits, features, pad_id)
            else:
                logits = logits[:, -1, :]
        loss = cross_entropy(logits, labels)
        return loss, {"accuracy": logits_accuracy(logits, labels)}

    return loss_fn
