"""train — losses, metrics, train state, fit/evaluate loops (reference L7)."""

from machine_learning_apache_spark_tpu.train.losses import (
    cross_entropy,
    masked_token_cross_entropy,
)
from machine_learning_apache_spark_tpu.train.metrics import (
    Mean,
    MetricBundle,
    Sum,
    accuracy,
    logits_accuracy,
)
from machine_learning_apache_spark_tpu.train.state import TrainState, make_optimizer
from machine_learning_apache_spark_tpu.train.checkpoint import (
    CheckpointManager,
    load_params,
    save_params,
)
from machine_learning_apache_spark_tpu.train.reshard import (
    BucketLayout,
    TopologyMismatch,
    elastic_restore,
    gather_spec,
    reshard_flat,
    reshard_flat_oracle,
)
from machine_learning_apache_spark_tpu.train.loop import (
    FitResult,
    classification_loss,
    evaluate,
    fit,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "cross_entropy",
    "masked_token_cross_entropy",
    "Mean",
    "MetricBundle",
    "Sum",
    "accuracy",
    "logits_accuracy",
    "TrainState",
    "make_optimizer",
    "CheckpointManager",
    "load_params",
    "save_params",
    "BucketLayout",
    "TopologyMismatch",
    "elastic_restore",
    "gather_spec",
    "reshard_flat",
    "reshard_flat_oracle",
    "FitResult",
    "classification_loss",
    "evaluate",
    "fit",
    "make_eval_step",
    "make_train_step",
]
