from machine_learning_apache_spark_tpu.train.metrics import (
    accuracy,
    Mean,
    Sum,
    MetricBundle,
)

__all__ = ["accuracy", "Mean", "Sum", "MetricBundle"]
