"""Train state: params + optimizer, the reference's ``model.parameters()`` ↔
``optimizer`` pair (``pytorch_multilayer_perceptron.py:93-96``), functional.

``make_optimizer`` covers the reference's optimizer vocabulary: SGD
(``pytorch_cnn.py:119`` lr=0.01, ``pytorch_multilayer_perceptron.py:96``
lr=0.03) and Adam (``pytorch_lstm.py:127`` lr=1e-3,
``pytorch_machine_translator.py:129``) — plus the training-scale knobs the
reference lacks: learning-rate schedules (warmup/cosine), global-norm
gradient clipping, and gradient accumulation (K microbatch grads averaged
into one update, so a per-chip-memory-bound batch can still train at the
large effective batch a pod would use).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct


def make_schedule(
    learning_rate: float,
    schedule: str | None = None,
    *,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    end_value: float = 0.0,
) -> float | optax.Schedule:
    """Learning-rate schedule: ``None``/``"constant"`` (the reference's fixed
    lr), ``"cosine"`` (cosine decay to ``end_value`` over ``total_steps``),
    or ``"warmup_cosine"`` (linear 0→lr over ``warmup_steps``, then cosine).
    """
    if schedule in (None, "constant"):
        if warmup_steps:
            return optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return learning_rate
    if schedule == "cosine":
        if total_steps is None:
            raise ValueError("cosine schedule requires total_steps")
        if warmup_steps:  # cosine-with-warmup IS warmup_cosine; honor it
            schedule = "warmup_cosine"
        else:
            return optax.cosine_decay_schedule(
                learning_rate, total_steps, alpha=end_value / learning_rate
            )
    if schedule == "warmup_cosine":
        if total_steps is None:
            raise ValueError("warmup_cosine schedule requires total_steps")
        return optax.warmup_cosine_decay_schedule(
            0.0,
            learning_rate,
            warmup_steps,
            max(total_steps, warmup_steps + 1),
            end_value=end_value,
        )
    raise ValueError(f"unknown schedule {schedule!r}")


def make_optimizer(
    name: str = "adam",
    learning_rate: float | optax.Schedule = 1e-3,
    *,
    schedule: str | None = None,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    grad_clip: float | None = None,
    accumulate_steps: int = 1,
    **kw,
) -> optax.GradientTransformation:
    """Optimizer with optional schedule, clipping, and accumulation.

    ``accumulate_steps=K`` wraps the chain in ``optax.MultiSteps``: K calls
    to ``update`` average their gradients and emit one real parameter update
    (zero updates in between), so ``fit`` needs no special handling — the
    effective batch is K × the loader batch.

    ZeRO-1 composition (``fit(dp_mode="zero1")``): the sharded update runs
    ``tx.update`` on each chip's 1/N gradient slice, so only elementwise
    chains compose — a ``grad_clip`` baked in HERE would clip by the
    shard-local norm, and ``accumulate_steps > 1`` keeps cross-element
    counters per shard. Pass ``grad_clip=`` to
    ``parallel.zero.make_zero1_step`` (a true global-norm clip via a scalar
    psum) and leave both knobs off the optimizer for that mode; see
    ``docs/PARALLELISM.md``.
    """
    if isinstance(learning_rate, (int, float)):
        lr = make_schedule(
            learning_rate,
            schedule,
            warmup_steps=warmup_steps,
            total_steps=total_steps,
        )
    else:
        if schedule is not None or warmup_steps:
            raise ValueError(
                "learning_rate is already a schedule callable; "
                "schedule/warmup_steps would be silently ignored"
            )
        lr = learning_rate
    name = name.lower()
    if name == "sgd":
        base = optax.sgd(lr, **kw)
    elif name == "adam":
        base = optax.adam(lr, **kw)
    elif name == "adamw":
        base = optax.adamw(lr, **kw)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if grad_clip is not None:
        base = optax.chain(optax.clip_by_global_norm(grad_clip), base)
    if accumulate_steps > 1:
        base = optax.MultiSteps(base, every_k_schedule=accumulate_steps)
    return base


class TrainState(struct.PyTreeNode):
    """Carry for the jitted train step: params, opt state, step counter.

    A lean re-implementation of ``flax.training.train_state.TrainState`` kept
    first-party so the sharding rules in ``parallel`` can address it without
    version skew.
    """

    step: jax.Array | int
    params: Any
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, tx) -> "TrainState":
        return cls(
            step=0, params=params, opt_state=tx.init(params), apply_fn=apply_fn, tx=tx
        )

    @property
    def opt_state_bytes(self) -> int:
        """Logical (unsharded) optimizer-state size in bytes — the number
        ZeRO-1 divides by the data-axis size; for the per-chip footprint
        of a sharded state see ``parallel.zero.opt_state_bytes_per_chip``."""
        return sum(
            leaf.nbytes
            for leaf in jax.tree.leaves(self.opt_state)
            if hasattr(leaf, "nbytes")
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )
