"""Train state: params + optimizer, the reference's ``model.parameters()`` ↔
``optimizer`` pair (``pytorch_multilayer_perceptron.py:93-96``), functional.

``make_optimizer`` covers the reference's optimizer vocabulary: SGD
(``pytorch_cnn.py:119`` lr=0.01, ``pytorch_multilayer_perceptron.py:96``
lr=0.03) and Adam (``pytorch_lstm.py:127`` lr=1e-3,
``pytorch_machine_translator.py:129``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct


def make_optimizer(name: str = "adam", learning_rate: float = 1e-3, **kw) -> optax.GradientTransformation:
    name = name.lower()
    if name == "sgd":
        return optax.sgd(learning_rate, **kw)
    if name == "adam":
        return optax.adam(learning_rate, **kw)
    if name == "adamw":
        return optax.adamw(learning_rate, **kw)
    raise ValueError(f"unknown optimizer {name!r}")


class TrainState(struct.PyTreeNode):
    """Carry for the jitted train step: params, opt state, step counter.

    A lean re-implementation of ``flax.training.train_state.TrainState`` kept
    first-party so the sharding rules in ``parallel`` can address it without
    version skew.
    """

    step: jax.Array | int
    params: Any
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, tx) -> "TrainState":
        return cls(
            step=0, params=params, opt_state=tx.init(params), apply_fn=apply_fn, tx=tx
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )
