"""Cross-topology checkpoint resharding — elastic resume (ROADMAP item 3).

Fault tolerance restarts a failed gang whole, at the same world size; a
preempted 8-chip job could never come back as 4 chips — exactly what
production preemption looks like. This module closes that gap: it maps
an N-rank sharded checkpoint (params + flat bucketed ZeRO-1 optimizer
slices + rng + ingest stream state) onto an M-rank gang, M < N or
M > N, including hybrid data x model meshes where only the data axis
changes.

Why it is tractable: the ZeRO-1 moments are flat 1-D fp32 vectors in
**bucket-major shard order** (``parallel.zero``: device ``i`` owns the
``i``-th 1/N slice of every bucket, concatenated), so re-mapping between
world sizes is pure byte-range redistribution — the portable-collective
formulation of "Memory-efficient array redistribution" (arxiv
2112.01075) — not a per-leaf puzzle. The stored vector is a
*permutation* of the logical flat vector that depends on ``(world,
buckets)``; both the padding and the bucket boundaries change with the
world size, so the remap un-permutes through the source
:class:`BucketLayout` and re-permutes through the destination one:

    stored[i * shard_len + base_k + t]  <->  logical[s_k + i * piece_k + t]

where bucket ``k`` spans ``[s_k, e_k)``, ``piece_k = (e_k - s_k) /
world`` and ``base_k`` is the cumulative piece length of earlier
buckets. :func:`gather_spec` intersects the two piecewise-linear maps
into contiguous ``(src_shard, src_off, dst_off, length)`` copies;
:func:`reshard_flat` applies them, and :func:`reshard_flat_oracle` is
the bit-exact single-host reference that reconstructs the logical
vector explicitly (the tests pin the two equal to the bit).

The run-level entry point is :func:`elastic_restore`: given a
:class:`~machine_learning_apache_spark_tpu.train.checkpoint.CheckpointManager`
whose directory follows the gang's ``ckpt_r<rank>`` group convention
and the old run's topology stamp (the ``meta_<step>.json`` sidecar's
``topology`` record), it agrees on one complete step across every old
rank directory, reads each rank's local shard payload, reshards the
flat optimizer leaves onto the new mesh, and reattaches everything into
the new run's (differently-sharded) template state. Params are
replicated under ZeRO-1, and rng/epoch/ingest sidecar state is
SPMD-identical across ranks, so those adopt directly; ingest
equalization is a function of the *current* world size and recomputes
on the new shard count (``ingest.rescatter_stream_state`` guards the
one genuinely rank-local case, ``shard='files'``).

Env contract (docs/FAULT_TOLERANCE.md "Elastic resume"):
``MLSPARK_ELASTIC=1`` — set by ``Distributor(elastic=True)`` in every
worker — lets ``fit(resume=True)`` route a topology-mismatched resume
through this module instead of raising :class:`TopologyMismatch`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Iterable, Sequence

import numpy as np

from machine_learning_apache_spark_tpu.utils import env as envcfg
from machine_learning_apache_spark_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_ELASTIC = "MLSPARK_ELASTIC"


def resolve_elastic(elastic: bool | None) -> bool:
    """Explicit argument > ``MLSPARK_ELASTIC`` env > False (the launcher
    gang plumbing: ``Distributor(elastic=True)`` sets the env var in
    every worker)."""
    if elastic is not None:
        return bool(elastic)
    return envcfg.get_bool(ENV_ELASTIC)


class TopologyMismatch(RuntimeError):
    """A resume found checkpoints written under a different topology and
    elastic resume is disabled. The message names BOTH topologies — a
    wrong-world resume must never silently misload per-rank shards."""


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Static description of how one flat fp32 vector is cut into
    bucket-major shards — the checkpoint-portable core of ``zero.py``'s
    ``_FlatPlan`` (no treedef/leaf shapes: resharding never needs them).

    ``world`` is the number of FLAT SHARDS (``axis_size * model_ways``
    on a hybrid mesh), not the process count: a data-axis-only change on
    a hybrid mesh is just a different ``world`` here.
    """

    total: int
    world: int
    padded: int
    shard_len: int
    buckets: tuple  # ((start, stop), ...) in flat padded coordinates

    def __post_init__(self) -> None:
        if self.padded != self.shard_len * self.world:
            raise ValueError(
                f"inconsistent layout: padded={self.padded} != "
                f"shard_len={self.shard_len} * world={self.world}"
            )
        stops = [0] + [e for _, e in self.buckets]
        starts = [s for s, _ in self.buckets] + [self.padded]
        if stops[:-1] != starts[: len(stops) - 1] or stops[-1] != self.padded:
            raise ValueError(
                f"buckets {self.buckets} do not partition [0, {self.padded})"
            )
        for s, e in self.buckets:
            if (e - s) % self.world:
                raise ValueError(
                    f"bucket ({s}, {e}) does not tile world={self.world}"
                )

    @classmethod
    def create(cls, total: int, world: int, bucket_bytes: int) -> "BucketLayout":
        """Mirror of ``zero.make_flat_plan``'s arithmetic (the tests pin
        the two equal): fp32-denominated bucket element counts rounded
        up to a multiple of the world, padding in the last bucket."""
        elems = max(bucket_bytes // 4, 1)
        elems = -(-elems // world) * world
        padded = -(-total // world) * world
        buckets = tuple(
            (start, min(start + elems, padded))
            for start in range(0, padded, elems)
        )
        return cls(
            total=total, world=world, padded=padded,
            shard_len=padded // world, buckets=buckets,
        )

    @classmethod
    def from_json(cls, data: dict) -> "BucketLayout":
        """Inverse of ``zero.plan_layout`` (the topology stamp's
        ``layout`` record)."""
        return cls(
            total=int(data["total"]),
            world=int(data["world"]),
            padded=int(data["padded"]),
            shard_len=int(data["shard_len"]),
            buckets=tuple((int(s), int(e)) for s, e in data["buckets"]),
        )

    def to_json(self) -> dict:
        return {
            "total": self.total, "world": self.world, "padded": self.padded,
            "shard_len": self.shard_len,
            "buckets": [[s, e] for s, e in self.buckets],
        }

    def segments(self) -> Iterable[tuple[int, int, int, int]]:
        """Yield ``(logical_lo, logical_hi, shard, stored_off)``: shard
        ``shard`` stores logical ``[lo, hi)`` at ``stored_off`` within
        its ``shard_len`` vector. Together the segments cover
        ``[0, padded)`` exactly once."""
        base = 0  # cumulative piece length of earlier buckets
        for s, e in self.buckets:
            piece = (e - s) // self.world
            for i in range(self.world):
                yield (s + i * piece, s + (i + 1) * piece, i, base)
            base += piece


def gather_spec(
    src: BucketLayout, dst: BucketLayout
) -> tuple[tuple[tuple[int, int, int, int], ...], ...]:
    """The resharded gather, as data: for every destination shard, the
    contiguous copies ``(src_shard, src_off, dst_off, length)`` (element
    units; multiply by the itemsize for byte ranges) that assemble it
    from the source shards.

    Only logical positions ``< total`` are copied: source padding is
    dropped and destination padding stays zero (the caller zero-fills),
    so layouts with different ``padded`` compose. Copies are produced by
    intersecting the two layouts' piecewise-linear stored<->logical maps
    — each overlap of a src segment with a dst segment is one contiguous
    run in both stored vectors.
    """
    if src.total != dst.total:
        raise ValueError(
            f"layouts describe different vectors: src total {src.total} "
            f"!= dst total {dst.total}"
        )
    src_segs = sorted(src.segments())  # sorted by logical_lo
    out: list[tuple] = []
    for j in range(dst.world):
        copies: list[tuple[int, int, int, int]] = []
        for dlo, dhi, shard, dbase in dst.segments():
            if shard != j:
                continue
            dhi = min(dhi, dst.total)
            for slo, shi, i, sbase in src_segs:
                lo, hi = max(dlo, slo), min(dhi, shi)
                if lo < hi:
                    copies.append(
                        (i, sbase + (lo - slo), dbase + (lo - dlo), hi - lo)
                    )
        copies.sort(key=lambda c: c[2])
        out.append(tuple(copies))
    return tuple(out)


def spec_byte_ranges(
    spec: Sequence[Sequence[tuple[int, int, int, int]]], itemsize: int = 4
) -> tuple[tuple[tuple[int, int, int, int], ...], ...]:
    """The same gather expressed over bucket BYTE ranges (what a remote
    blob-range reader would fetch): every offset/length scaled by the
    element ``itemsize`` (fp32 master vectors: 4)."""
    return tuple(
        tuple((i, so * itemsize, do * itemsize, ln * itemsize)
              for i, so, do, ln in copies)
        for copies in spec
    )


def reshard_flat(
    shards: Sequence[np.ndarray],
    src: BucketLayout,
    dst: BucketLayout,
    spec=None,
) -> list[np.ndarray]:
    """Redistribute a stored flat vector from ``src``'s N shards to
    ``dst``'s M shards by applying :func:`gather_spec`'s byte-range
    copies. Destination padding is zero (matching what ``zero.py``'s
    fused step maintains: the pad never accumulates nonzero state under
    an elementwise optimizer fed zero pad gradients)."""
    if len(shards) != src.world:
        raise ValueError(f"expected {src.world} shards, got {len(shards)}")
    arrs = [np.asarray(s) for s in shards]
    for i, a in enumerate(arrs):
        if a.shape != (src.shard_len,):
            raise ValueError(
                f"shard {i} has shape {a.shape}, expected ({src.shard_len},)"
            )
    dtype = arrs[0].dtype
    spec = gather_spec(src, dst) if spec is None else spec
    out = [np.zeros(dst.shard_len, dtype=dtype) for _ in range(dst.world)]
    for j, copies in enumerate(spec):
        for i, so, do, ln in copies:
            out[j][do:do + ln] = arrs[i][so:so + ln]
    return out


def reshard_flat_oracle(
    shards: Sequence[np.ndarray], src: BucketLayout, dst: BucketLayout
) -> list[np.ndarray]:
    """Bit-exact single-host reference: reconstruct the LOGICAL vector
    explicitly through ``src``'s coordinate map, then scatter it through
    ``dst``'s. ``reshard_flat`` must agree to the bit (tests pin it);
    this form is O(padded) memory, the gather form streams ranges."""
    arrs = [np.asarray(s) for s in shards]
    logical = np.zeros(src.padded, dtype=arrs[0].dtype)
    for lo, hi, i, base in src.segments():
        logical[lo:hi] = arrs[i][base:base + (hi - lo)]
    logical = logical[:src.total]
    out = [np.zeros(dst.shard_len, dtype=logical.dtype) for _ in range(dst.world)]
    for lo, hi, j, base in dst.segments():
        hi = min(hi, dst.total)
        if lo < hi:
            out[j][base:base + (hi - lo)] = logical[lo:hi]
    return out


# -- run-level elastic restore ------------------------------------------------

def _is_flat_opt_leaf(template_leaf, layout: BucketLayout | None) -> bool:
    """A ZeRO-1 flat moment vector: 1-D, exactly the padded length of
    the run's layout. Scalar counts and any other opt leaves replicate."""
    return (
        layout is not None
        and getattr(template_leaf, "ndim", 0) == 1
        and int(template_leaf.shape[0]) == layout.padded
    )


def _stamp_layout(stamp: dict | None) -> BucketLayout | None:
    if not stamp or not stamp.get("layout"):
        return None
    return BucketLayout.from_json(stamp["layout"])


def elastic_restore(
    checkpointer, template, *, old_stamp: dict, step: int | None = None
):
    """Restore an old-topology checkpoint group into ``template`` (the
    NEW topology's state). Returns ``(state, step, meta)`` like
    ``CheckpointManager.restore_latest_valid``, or None when the group
    has no complete step to agree on.

    - the step is the group-durable one: the newest step whose data is
      finalized in every OLD rank's directory (orbax finalization is
      atomic, so a plain step directory is complete even when the dead
      rank's ``latest`` pointer never flushed), preferring a step whose
      authority sidecar (rng / epoch / topology) survives;
    - flat optimizer vectors are reassembled from every old rank's local
      shard payload and resharded through ``gather_spec``; everything
      else (params under ZeRO-1, scalar counts, step) is replicated and
      adopts from the lowest old rank;
    - the returned ``meta`` is the agreed step's sidecar (rng / epoch /
      ingest state are SPMD-identical across ranks — the caller reuses
      its normal resume path on it).
    """
    import jax

    from machine_learning_apache_spark_tpu.train import checkpoint as _ckpt

    old_world = int(old_stamp.get("world_size", 1))
    dirs = checkpointer.group_rank_dirs()
    if dirs is None:
        if old_world != 1:
            raise TopologyMismatch(
                f"checkpoint stamp names a {old_world}-rank gang but "
                f"{checkpointer.directory!r} does not follow the "
                "ckpt_r<rank> group convention — the peer rank "
                "directories cannot be located for resharding"
            )
        dirs = {0: checkpointer.directory}
    missing = [r for r in range(old_world) if r not in dirs]
    if missing:
        raise TopologyMismatch(
            f"elastic resume needs every old rank's checkpoint directory; "
            f"missing ckpt_r<k> for ranks {missing} of the old "
            f"{old_world}-rank gang"
        )
    if step is None:
        chosen = _agreed_step_and_stamp(dirs, old_stamp)
        if chosen is None:
            log.warning(
                "elastic resume found no step durable on every rank of the "
                "old %d-rank group; starting fresh", old_world,
            )
            return None
        step, stamp = chosen
        stamp_world = int(stamp.get("world_size", old_world))
        if stamp_world != old_world:
            # Repeated shrinks can leave the newest sidecar naming a gang
            # whose own checkpoint never became group-durable; the agreed
            # step's OWN stamp is the layout its payload was written under.
            log.info(
                "elastic resume: newest stamp names a %d-rank gang but the "
                "agreed step %d was written by a %d-rank gang; resharding "
                "from the step's own topology", old_world, step, stamp_world,
            )
            old_stamp, old_world = stamp, stamp_world
    old_dirs = {r: dirs[r] for r in range(old_world)}

    new_stamp = _ckpt.topology_stamp(template)
    if old_stamp.get("dp_mode", "replicated") != new_stamp.get("dp_mode"):
        raise TopologyMismatch(
            f"cannot reshard across dp modes: checkpoint was "
            f"{old_stamp.get('dp_mode')!r}, this run is "
            f"{new_stamp.get('dp_mode')!r}"
        )
    src = _stamp_layout(old_stamp)
    dst = _stamp_layout(new_stamp)
    if (src is None) != (dst is None):
        raise TopologyMismatch(
            f"checkpoint layout {old_stamp.get('layout')} is incompatible "
            f"with this run's layout {new_stamp.get('layout')}"
        )
    if src is not None and src.total != dst.total:
        raise TopologyMismatch(
            f"checkpoint flat vector has {src.total} elements, this run's "
            f"has {dst.total} — different model/optimizer, not a topology "
            "change"
        )

    target = _ckpt.detached_payload(template)
    if src is not None:
        if src.world % old_world:
            raise TopologyMismatch(
                f"old layout world {src.world} does not divide over "
                f"{old_world} processes"
            )
        per_old = src.world // old_world
        local_len = src.shard_len * per_old
        # Per-old-rank restore target: same tree, flat vectors swapped
        # for that rank's local shard length.
        def _old_target():
            return jax.tree.map(
                lambda t, leaf: (
                    np.zeros(local_len, dtype=t.dtype)
                    if _is_flat_opt_leaf(leaf, dst) else t
                ),
                target, _template_payload(template),
            )
    else:
        def _old_target():
            return {k: v for k, v in target.items()}

    payloads = {}
    for r in sorted(old_dirs):
        payloads[r] = _ckpt.read_raw_payload(old_dirs[r], step, _old_target())
        if src is None:
            # Replicated state: one rank's payload is the whole state.
            break

    base = payloads[min(payloads)]
    if src is not None:
        spec = gather_spec(src, dst)
        # Old rank r stored its local chunk of the flat vector: `per_old`
        # consecutive shards (process-major device order), shard_len each.
        tmpl_opt_leaves, opt_treedef = jax.tree.flatten(template.opt_state)
        per_rank_opt = {
            r: jax.tree.flatten(payloads[r]["opt_state"])[0]
            for r in payloads
        }
        new_opt_leaves = []
        for li, tmpl_leaf in enumerate(tmpl_opt_leaves):
            if not _is_flat_opt_leaf(tmpl_leaf, dst):
                new_opt_leaves.append(per_rank_opt[min(payloads)][li])
                continue
            shards = []
            for r in sorted(payloads):
                flat = np.asarray(per_rank_opt[r][li])
                shards.extend(
                    flat[c * src.shard_len:(c + 1) * src.shard_len]
                    for c in range(per_old)
                )
            new_shards = reshard_flat(shards, src, dst, spec=spec)
            new_opt_leaves.append(np.concatenate(new_shards))
        new_opt = jax.tree.unflatten(opt_treedef, new_opt_leaves)
    else:
        new_opt = base["opt_state"]

    state = template.replace(
        step=_ckpt.attach_local(base["step"], _template_payload(template)["step"]),
        params=jax.tree.map(
            _ckpt.attach_local, base["params"], _template_payload(template)["params"]
        ),
        opt_state=jax.tree.map(
            _ckpt.attach_local, new_opt, _template_payload(template)["opt_state"]
        ),
    )
    meta = _ckpt.read_meta_at(old_dirs[min(old_dirs)], step)
    log.info(
        "elastic restore: step %d resharded from %d-rank layout onto %s",
        step, old_world, new_stamp.get("world_size"),
    )
    return state, int(step), meta


def _agreed_step_and_stamp(dirs, fallback_stamp):
    """Pick the restore step and the topology it was actually written
    under, TOGETHER. Scans the authority (lowest-rank) directory's
    sidecars newest-first and accepts the first step that is durable in
    every directory of the gang named by that step's own stamp — after
    repeated shrinks the newest sidecar and the newest group-durable step
    can name different world sizes, and resharding a payload with the
    wrong layout would interleave shards from the wrong ranks. Falls
    back to the plain durable-data intersection under ``fallback_stamp``
    when no stamped step qualifies (e.g. every sidecar was lost with the
    crashed ranks)."""
    from machine_learning_apache_spark_tpu.train import checkpoint as _ckpt

    auth = dirs[min(dirs)]
    durable = {r: _ckpt.durable_steps_of(d) for r, d in dirs.items()}
    for s in _ckpt.sidecar_steps_of(auth):
        meta = _ckpt.read_meta_at(auth, s) or {}
        stamp = meta.get("topology")
        if not stamp:
            continue
        w = int(stamp.get("world_size", 1))
        if any(r not in dirs for r in range(w)):
            continue
        if all(s in durable[r] for r in range(w)):
            return s, stamp
    w = int(fallback_stamp.get("world_size", 1))
    if any(r not in dirs for r in range(w)):
        return None
    s = _ckpt.group_durable_step({r: dirs[r] for r in range(w)})
    return (s, fallback_stamp) if s is not None else None


def _template_payload(template) -> dict:
    """The live (jax.Array) payload tree matching the checkpoint payload
    shape — what ``attach_local`` needs as its per-leaf template."""
    import jax

    return {
        "step": template.step if hasattr(template.step, "sharding")
        else np.int64(jax.device_get(template.step)),
        "params": template.params,
        "opt_state": template.opt_state,
    }


__all__ = [
    "ENV_ELASTIC",
    "BucketLayout",
    "TopologyMismatch",
    "elastic_restore",
    "gather_spec",
    "reshard_flat",
    "reshard_flat_oracle",
    "resolve_elastic",
    "spec_byte_ranges",
]
