"""Metrics — the reference's vocabulary, implemented once.

The reference defines ``accuracy_fn`` (eq-count percentage) six separate times
(``pytorch_cnn.py:111-114`` et al.) and accumulates ``total_test_loss`` by
hand in every script. This module is the single implementation: jit-friendly
metric functions plus tiny host-side accumulators.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp


def accuracy(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Percentage of exact label matches — the reference ``accuracy_fn``
    (``pytorch_cnn.py:111-114``): ``eq(y_true, y_pred).sum() / len * 100``."""
    correct = jnp.sum(y_true == y_pred)
    return correct / y_true.size * 100.0


def logits_accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """softmax→argmax→accuracy, the reference eval pattern
    (``pytorch_multilayer_perceptron.py:135-139``). Softmax is monotonic so
    argmax of logits suffices."""
    return accuracy(labels, jnp.argmax(logits, axis=-1))


@dataclass
class Sum:
    """Running sum — ``total_train_loss += loss`` (``pytorch_cnn.py:131``)."""

    total: float = 0.0
    count: int = 0

    def update(self, value, n: int = 1) -> None:
        self.total += float(value)
        self.count += n

    def compute(self) -> float:
        return self.total


@dataclass
class Mean(Sum):
    """Weighted running mean: ``update(value, n)`` treats ``value`` as a mean
    over ``n`` samples (n=1 for per-step scalars)."""

    def update(self, value, n: int = 1) -> None:
        self.total += float(value) * n
        self.count += n

    def compute(self) -> float:
        return self.total / max(self.count, 1)


@dataclass
class MetricBundle:
    """Named accumulators with one ``log_line`` in the reference's print
    format (``distributed_cnn.py:188-191``)."""

    metrics: dict = field(default_factory=dict)

    def sum(self, name: str) -> Sum:
        m = self.metrics.setdefault(name, Sum())
        assert type(m) is Sum, f"metric {name!r} already registered as {type(m).__name__}"
        return m

    def mean(self, name: str) -> Mean:
        m = self.metrics.setdefault(name, Mean())
        assert isinstance(m, Mean)
        return m

    def compute(self) -> dict:
        return {k: v.compute() for k, v in self.metrics.items()}

    def log_line(self) -> str:
        return " | ".join(f"{k}: {v:.5f}" for k, v in self.compute().items())
