"""Metrics — the reference's vocabulary, implemented once.

The reference defines ``accuracy_fn`` (eq-count percentage) six separate times
(``pytorch_cnn.py:111-114`` et al.) and accumulates ``total_test_loss`` by
hand in every script. This module is the single implementation: jit-friendly
metric functions plus tiny host-side accumulators.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp


def accuracy(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Percentage of exact label matches — the reference ``accuracy_fn``
    (``pytorch_cnn.py:111-114``): ``eq(y_true, y_pred).sum() / len * 100``."""
    correct = jnp.sum(y_true == y_pred)
    return correct / y_true.size * 100.0


def logits_accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """softmax→argmax→accuracy, the reference eval pattern
    (``pytorch_multilayer_perceptron.py:135-139``). Softmax is monotonic so
    argmax of logits suffices."""
    return accuracy(labels, jnp.argmax(logits, axis=-1))


def strip_special_ids(
    ids, *, pad_id: int = 0, sos_id: int = 1, eos_id: int = 2
) -> list[list[int]]:
    """Decoder output rows → clean token-id lists: drop the leading ``sos``,
    cut at the first ``eos``, drop pads — the form BLEU scores."""
    import numpy as np

    out = []
    for row in np.asarray(ids):
        toks = [int(t) for t in row]
        if toks and toks[0] == sos_id:
            toks = toks[1:]
        if eos_id in toks:
            toks = toks[: toks.index(eos_id)]
        out.append([t for t in toks if t != pad_id])
    return out


def corpus_bleu(
    candidates: list[list[int]],
    references: list[list[int]],
    *,
    max_n: int = 4,
    smooth: bool = True,
) -> float:
    """Corpus BLEU over token-id sequences (Papineni et al. 2002): clipped
    modified n-gram precisions (n ≤ ``max_n``) geometric-mean'd with a
    brevity penalty — the standard MT quality metric the reference's
    translation driver never computes (it reports loss only,
    ``pytorch_machine_translator.py:189``). Host-side, pure Python.

    ``smooth=True`` applies add-one smoothing (Lin & Och 2004 method 1 style)
    to zero higher-order counts so short corpora don't collapse to 0.
    """
    from collections import Counter
    from math import exp, log

    if len(candidates) != len(references):
        raise ValueError(
            f"{len(candidates)} candidates vs {len(references)} references"
        )
    if not candidates:
        return 0.0

    def ngrams(seq, n):
        return Counter(tuple(seq[i : i + n]) for i in range(len(seq) - n + 1))

    matched = [0] * max_n
    total = [0] * max_n
    cand_len = ref_len = 0
    for cand, ref in zip(candidates, references):
        cand_len += len(cand)
        ref_len += len(ref)
        for n in range(1, max_n + 1):
            cn, rn = ngrams(cand, n), ngrams(ref, n)
            total[n - 1] += max(len(cand) - n + 1, 0)
            matched[n - 1] += sum(min(c, rn[g]) for g, c in cn.items())
    precisions = []
    for m, t in zip(matched, total):
        if t == 0:
            precisions.append(None)  # no n-grams that long anywhere; skip
        elif m == 0:
            if not smooth:
                return 0.0
            precisions.append(1.0 / (2.0 * t))
        else:
            precisions.append(m / t)
    precisions = [p for p in precisions if p is not None]
    if not precisions:
        return 0.0
    geo = exp(sum(log(p) for p in precisions) / len(precisions))
    bp = 1.0 if cand_len > ref_len else exp(1.0 - ref_len / max(cand_len, 1))
    return bp * geo


@dataclass
class Sum:
    """Running sum — ``total_train_loss += loss`` (``pytorch_cnn.py:131``)."""

    total: float = 0.0
    count: int = 0

    def update(self, value, n: int = 1) -> None:
        self.total += float(value)
        self.count += n

    def compute(self) -> float:
        return self.total


@dataclass
class Mean(Sum):
    """Weighted running mean: ``update(value, n)`` treats ``value`` as a mean
    over ``n`` samples (n=1 for per-step scalars)."""

    def update(self, value, n: int = 1) -> None:
        self.total += float(value) * n
        self.count += n

    def compute(self) -> float:
        return self.total / max(self.count, 1)


class MetricsLogger:
    """Append-only JSONL metrics sink — the structured counterpart of the
    reference's ``print()``-only observability (SURVEY.md §5 metrics/logging).
    Each ``write(record)`` appends one JSON line stamped with wall time.

    >>> with MetricsLogger(path) as m:
    ...     m.write({"kind": "epoch", "epoch": 0, "loss": 1.2})
    >>> MetricsLogger.read(path)
    [{"ts": ..., "kind": "epoch", ...}]
    """

    def __init__(self, path: str):
        import os

        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fh = open(self.path, "a", buffering=1)  # line-buffered

    def write(self, record: dict) -> None:
        import json
        import time

        self._fh.write(json.dumps({"ts": time.time(), **record}) + "\n")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        import json

        with open(path) as fh:
            return [json.loads(line) for line in fh if line.strip()]


@dataclass
class MetricBundle:
    """Named accumulators with one ``log_line`` in the reference's print
    format (``distributed_cnn.py:188-191``)."""

    metrics: dict = field(default_factory=dict)

    def sum(self, name: str) -> Sum:
        m = self.metrics.setdefault(name, Sum())
        assert type(m) is Sum, f"metric {name!r} already registered as {type(m).__name__}"
        return m

    def mean(self, name: str) -> Mean:
        m = self.metrics.setdefault(name, Mean())
        assert isinstance(m, Mean)
        return m

    def compute(self) -> dict:
        return {k: v.compute() for k, v in self.metrics.items()}

    def log_line(self) -> str:
        return " | ".join(f"{k}: {v:.5f}" for k, v in self.compute().items())
